"""Checkpoint integrity: CRC sidecar verification, atomic save, and
salvage-around-corruption (resilience.py), exercised with deterministic
fault injection (faults.py).

Every corruption class we have to survive is injected here: truncated
files, flipped payload bits, a wrecked endianness magic, a lost
sidecar, corruption inside ragged (variable-size) payloads, and I/O
errors during the save itself. The golden ``.dc`` byte format is
pinned separately by tests/test_golden.py — the sidecar lives in its
own file, so byte identity of the checkpoint is untouched (re-checked
here too)."""

import json
import os

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dccrg_tpu import faults, resilience
from dccrg_tpu.resilience import CheckpointCorruptionError
from golden_fixture import GOLDEN_SCHEMA, GOLDEN_VARIABLE, build_golden_grid

pytestmark = pytest.mark.faultinject

HEADER = b"integrity-v1\n"
# small chunks so single corruptions map onto a few cells, not the
# whole payload
CHUNK = 128


@pytest.fixture
def saved(tmp_path):
    g = build_golden_grid(Mesh(np.array(jax.devices()[:4]), ("dev",)))
    fn = str(tmp_path / "ck.dc")
    resilience.save_checkpoint(g, fn, header=HEADER,
                               variable=GOLDEN_VARIABLE, chunk_bytes=CHUNK)
    return g, fn


def _load(fn, strict=True):
    return resilience.load_checkpoint(
        fn, GOLDEN_SCHEMA, header_size=len(HEADER),
        variable=GOLDEN_VARIABLE, strict=strict)


def _assert_equal_on(g_ref, g_got, cells):
    if not len(cells):
        return
    counts = g_ref.get("count", cells)
    for name in GOLDEN_SCHEMA:
        want = g_ref.get(name, cells)
        got = g_got.get(name, cells)
        if name in GOLDEN_VARIABLE:
            # ragged field: only rows < count are stored/restored
            keep = np.arange(want.shape[1])[None, :] < counts[:, None]
            want = np.where(keep[..., None], want, 0)
            got = np.where(keep[..., None], got, 0)
        np.testing.assert_array_equal(got, want, err_msg=f"field {name!r}")


def test_clean_roundtrip(saved):
    g, fn = saved
    assert os.path.exists(fn + ".crc")
    assert resilience.verify_checkpoint(fn) == []
    g2, header, report = _load(fn)
    assert header == HEADER
    assert report.clean
    _assert_equal_on(g, g2, np.asarray(g.plan.cells))


def test_sidecar_does_not_change_dc_bytes(saved, tmp_path):
    """save_checkpoint writes byte-identical .dc content to the plain
    (golden-pinned) save path."""
    g, fn = saved
    plain = tmp_path / "plain.dc"
    g.save_grid_data(str(plain), header=HEADER, variable=GOLDEN_VARIABLE)
    assert plain.read_bytes() == open(fn, "rb").read()


def test_flipped_payload_bit_detected_and_salvaged(saved):
    g, fn = saved
    rec = json.load(open(fn + ".crc"))
    # flip one bit in the middle of the payload
    byte = (rec["payload_start"] + rec["file_bytes"]) // 2
    faults.flip_bit(fn, byte, bit=5)
    with pytest.raises(CheckpointCorruptionError, match=r"payload chunk \d+"):
        _load(fn)
    g2, _, report = _load(fn, strict=False)
    assert len(report.bad_chunks) == 1
    assert len(report.corrupt_cells)
    # every cell OUTSIDE the bad chunk is recovered exactly
    ok = np.setdiff1d(np.asarray(g.plan.cells), report.corrupt_cells)
    assert len(ok) > len(report.corrupt_cells)  # fine-grained salvage
    _assert_equal_on(g, g2, ok)
    # corrupt cells come back zeroed, not garbage
    np.testing.assert_array_equal(
        g2.get("density", report.corrupt_cells),
        np.zeros(len(report.corrupt_cells), np.float32))


def test_every_single_byte_flip_is_detected(saved):
    """ANY single flipped byte anywhere in the file fails verification
    (sampled across the whole file for speed, always including the
    first/last byte and chunk boundaries)."""
    _, fn = saved
    size = os.path.getsize(fn)
    good = open(fn, "rb").read()
    probe = sorted({0, size - 1, CHUNK, CHUNK + 1, size // 2}
                   | set(range(7, size, max(1, size // 19))))
    for byte in probe:
        faults.flip_bit(fn, byte, bit=1)
        assert resilience.verify_checkpoint(fn), f"flip at {byte} missed"
        with open(fn, "wb") as f:
            f.write(good)
    assert resilience.verify_checkpoint(fn) == []


def test_truncated_file(saved):
    g, fn = saved
    faults.truncate_file(fn, 2 * CHUNK + 7)
    with pytest.raises(CheckpointCorruptionError):
        _load(fn)
    g2, _, report = _load(fn, strict=False)
    ok = np.setdiff1d(np.asarray(g.plan.cells), report.corrupt_cells)
    _assert_equal_on(g, g2, ok)


def test_wrong_endianness_magic(saved):
    """A corrupt magic is metadata corruption: named as such, and not
    salvageable in either mode. Without a sidecar the legacy parse
    error still fires."""
    _, fn = saved
    faults.flip_bit(fn, len(HEADER) + 2, bit=0)  # inside the magic u64
    with pytest.raises(CheckpointCorruptionError, match="metadata block"):
        _load(fn)
    with pytest.raises(CheckpointCorruptionError, match="metadata"):
        _load(fn, strict=False)
    os.unlink(fn + ".crc")  # no sidecar: the parser's own check fires
    with pytest.raises(ValueError, match="bad endianness magic"):
        _load(fn, strict=False)


def test_missing_sidecar(saved):
    g, fn = saved
    os.unlink(fn + ".crc")
    with pytest.raises(CheckpointCorruptionError, match="sidecar"):
        _load(fn)
    g2, _, report = _load(fn, strict=False)
    assert report.sidecar_missing
    _assert_equal_on(g, g2, np.asarray(g.plan.cells))


def test_ragged_payload_corruption(saved):
    """Corruption inside a variable-size (ragged) cell's rows: strict
    names the chunk; salvage zeroes that cell's count (no corrupt-count
    explosion) and recovers everything else."""
    g, fn = saved
    rec = json.load(open(fn + ".crc"))
    # the LAST bytes of the payload belong to the highest-offset cell's
    # ragged tail (pos rows, GOLDEN_VARIABLE truncates by count)
    faults.flip_bit(fn, rec["file_bytes"] - 3, bit=7)
    with pytest.raises(CheckpointCorruptionError, match="payload chunk"):
        _load(fn)
    g2, _, report = _load(fn, strict=False)
    assert len(report.corrupt_cells)
    ok = np.setdiff1d(np.asarray(g.plan.cells), report.corrupt_cells)
    _assert_equal_on(g, g2, ok)
    # the corrupt ragged rows come back zeroed (the cells' counts live
    # in an earlier, intact chunk and survive — consistent state, no
    # corrupt-count explosion)
    pos = g2.get("pos", report.corrupt_cells)
    counts = g2.get("count", report.corrupt_cells)
    for i, c in enumerate(counts):
        np.testing.assert_array_equal(pos[i, :c], 0.0)


def test_trailing_garbage_detected_but_salvage_keeps_all_cells(saved):
    """Appended garbage past the recorded size fails verification, but
    the recorded byte range is intact — salvage trims the tail and
    recovers EVERY cell (no destructive zeroing of the last chunk)."""
    g, fn = saved
    with open(fn, "ab") as f:
        f.write(b"\xde\xad" * 5)
    assert resilience.verify_checkpoint(fn)
    with pytest.raises(CheckpointCorruptionError, match="trailing"):
        _load(fn)
    g2, _, report = _load(fn, strict=False)
    assert not len(report.corrupt_cells)
    _assert_equal_on(g, g2, np.asarray(g.plan.cells))


def test_corrupt_sidecar_geometry_rejected_not_hung(saved):
    """A sidecar damaged into parseable-but-implausible JSON (zero
    chunk size) raises CheckpointCorruptionError instead of hanging
    the chunk-range walk."""
    _, fn = saved
    rec = json.load(open(fn + ".crc"))
    rec["chunk_bytes"] = 0
    json.dump(rec, open(fn + ".crc", "w"))
    with pytest.raises(CheckpointCorruptionError, match="sidecar"):
        resilience.verify_checkpoint(fn)
    rec["chunk_bytes"] = "lots"
    json.dump(rec, open(fn + ".crc", "w"))
    with pytest.raises(CheckpointCorruptionError, match="sidecar"):
        _load(fn, strict=False)


def test_failed_rename_keeps_old_checkpoint_verifiable(saved, monkeypatch):
    """A save whose rename fails on every retry must leave the OLD
    checkpoint — the intact one still under the final name — with its
    sidecar, so strict load and rollback still accept it."""
    g, fn = saved
    real_replace = os.replace

    def bad_replace(src, dst):
        if dst == fn:
            raise OSError("injected rename failure")
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", bad_replace)
    with pytest.raises(OSError, match="rename"):
        resilience.save_checkpoint(g, fn, header=HEADER,
                                   variable=GOLDEN_VARIABLE,
                                   chunk_bytes=CHUNK, retries=1,
                                   backoff=0.0)
    monkeypatch.undo()
    assert resilience.verify_checkpoint(fn) == []


def test_truncated_sidecar_crc_list_rejected(saved):
    """A sidecar whose crc list lost tail entries (still valid JSON,
    plausible geometry) must be rejected — otherwise the uncovered
    trailing payload chunks would verify as clean."""
    _, fn = saved
    rec = json.load(open(fn + ".crc"))
    assert len(rec["crc32"]) >= 2
    rec["crc32"] = rec["crc32"][:-1]
    json.dump(rec, open(fn + ".crc", "w"))
    with pytest.raises(CheckpointCorruptionError, match="sidecar"):
        resilience.verify_checkpoint(fn)


def test_transient_io_error_retries(saved, tmp_path):
    """A transient I/O failure during save retries and succeeds; the
    fault log records exactly one firing."""
    g, fn = saved
    out = str(tmp_path / "retry.dc")
    plan = faults.FaultPlan()
    plan.io_error(times=1)
    with plan:
        resilience.save_checkpoint(g, out, header=HEADER,
                                   variable=GOLDEN_VARIABLE, backoff=0.0)
    assert plan.fired("checkpoint.write") == 1
    assert resilience.verify_checkpoint(out) == []


def test_failed_save_preserves_previous_checkpoint(saved, tmp_path):
    """A save that dies mid payload stream (torn temp file) never
    replaces the previous checkpoint, and leaves no temp litter."""
    g, fn = saved
    before = open(fn, "rb").read()
    plan = faults.FaultPlan()
    plan.chunk_io_error(times=faults.EVERY)  # every attempt dies
    with plan, pytest.raises(OSError):
        resilience.save_checkpoint(g, fn, header=HEADER,
                                   variable=GOLDEN_VARIABLE,
                                   retries=1, backoff=0.0)
    assert open(fn, "rb").read() == before
    assert resilience.verify_checkpoint(fn) == []
    assert not [p for p in os.listdir(os.path.dirname(fn))
                if ".tmp." in p]


@pytest.mark.deltackpt
@pytest.mark.parametrize("broken", [0, 1, 2, 3])
@pytest.mark.parametrize("damage", ["flip", "truncate", "delete"])
def test_chain_salvage_falls_back_to_verifying_prefix(tmp_path, broken,
                                                      damage):
    """Incremental-checkpoint chain salvage: corrupt/truncate/delete
    EACH link position of a keyframe+3-delta chain. load_checkpoint
    raises the typed DeltaChainError naming the broken link, and
    resume_latest falls back to the newest state the surviving prefix
    can restore (an older delta, the keyframe, or — keyframe gone —
    nothing)."""
    from test_delta_checkpoint import SCHEMA, _plant_chain

    from dccrg_tpu import resilience, supervise
    from dccrg_tpu.resilience import DeltaChainError

    g, store, paths, states = _plant_chain(tmp_path)
    victim = paths[broken]
    if damage == "flip":
        faults.flip_bit(victim, os.path.getsize(victim) - 3, bit=2)
    elif damage == "truncate":
        faults.truncate_file(victim, os.path.getsize(victim) // 2)
    else:
        os.unlink(victim)
        os.unlink(resilience.sidecar_path(victim))
    with pytest.raises(DeltaChainError) as ei:
        resilience.load_checkpoint(paths[-1], SCHEMA,
                                   load_balancing_method="block")
    assert os.path.basename(victim) in str(ei.value)
    info = supervise.resume_latest(tmp_path, SCHEMA,
                                   load_balancing_method="block")
    if broken == 0 and damage != "delete":
        # dead keyframe, salvage leg: flip/truncate damage may still
        # salvage the keyframe's intact chunks; require a typed
        # non-strict outcome, never a wrong strict success
        assert info is None or info.salvaged or info.step < len(paths) - 1
        return
    if broken == 0:
        assert info is None  # nothing survives a deleted keyframe
        return
    assert info is not None and not info.salvaged
    assert info.step == broken - 1  # newest link BEFORE the break
    cells = g.plan.cells
    np.testing.assert_array_equal(
        np.asarray(info.grid.get("rho", cells)), states[broken - 1])


def test_corruption_injected_through_plan(saved, tmp_path):
    """The FaultPlan file-corruption path (seeded random bit flip after
    a save) is caught by verification — the end-to-end story a torn
    disk gives us."""
    g, _ = saved
    out = str(tmp_path / "planned.dc")
    plan = faults.FaultPlan(seed=11)
    plan.bit_flip(times=1)
    with plan:
        resilience.save_checkpoint(g, out, header=HEADER,
                                   variable=GOLDEN_VARIABLE,
                                   chunk_bytes=CHUNK)
    assert plan.fired("checkpoint.file") == 1
    assert resilience.verify_checkpoint(out)
