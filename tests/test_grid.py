"""Distributed grid tests on the virtual 8-device CPU mesh.

End-to-end strategy follows the reference (SURVEY.md section 4):
known-answer oscillator checks for game of life
(examples/simple_game_of_life.cpp:122-158) and single-device vs
multi-device equivalence (the reference requires identical results for
any process count, tests/README:5-6).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID, Grid, default_mesh
from dccrg_tpu.models.game_of_life import GameOfLife


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def gol_id(x, y, nx=10):
    return 1 + x + y * nx


# ---------------------------------------------------------------------
# construction & views

def test_initialize_and_views():
    g = (
        Grid(cell_data={"v": jnp.float32})
        .set_initial_length((4, 4, 4))
        .set_neighborhood_length(1)
        .initialize(mesh_of(8))
    )
    assert len(g.get_cells()) == 64
    local = g.local_cells()
    assert len(local) == 64
    inner = g.inner_cells()
    outer = g.outer_cells()
    assert len(inner) + len(outer) == 64
    # every device owns some cells
    assert len(np.unique(local.owner)) == 8
    # remote cells exist on a multi-device mesh
    assert len(g.remote_cells()) > 0


def test_single_device_grid():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((3, 3, 1)).initialize(mesh_of(1))
    assert len(g.inner_cells()) == 9
    assert len(g.outer_cells()) == 0
    g.update_copies_of_remote_neighbors()  # no-op, must not fail


def test_get_set_roundtrip():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((4, 4, 1)).initialize(mesh_of(4))
    ids = np.array([1, 7, 16], dtype=np.uint64)
    g.set("v", ids, np.array([1.5, 2.5, 3.5], dtype=np.float32))
    np.testing.assert_allclose(g.get("v", ids), [1.5, 2.5, 3.5])
    assert g.get("v", np.uint64(2)) == 0.0
    with pytest.raises(KeyError):
        g.get("v", np.uint64(99))


def test_neighbor_queries():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((4, 4, 4)).initialize(mesh_of(2))
    nbrs = g.get_neighbors_of(22)  # interior cell
    assert len(nbrs) == 26
    ids = [n for n, _ in nbrs]
    assert 21 in ids and 23 in ids and 22 - 16 in ids
    # face neighbors with direction codes
    faces = g.get_face_neighbors_of(22)
    assert sorted(faces) == sorted(
        [(21, -1), (23, 1), (18, -2), (26, 2), (6, -3), (38, 3)]
    )
    # neighbors_to inverse of symmetric hood
    tos = [n for n, _ in g.get_neighbors_to(22)]
    assert sorted(tos) == sorted(ids)


def test_neighbors_of_at_offset():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((4, 4, 4)).initialize(mesh_of(2))
    assert g.get_neighbors_of_at_offset(22, 1, 0, 0) == [(23, (1, 0, 0))]
    assert g.get_neighbors_of_at_offset(22, -1, -1, 0) == [(17, (-1, -1, 0))]
    assert g.get_neighbors_of_at_offset(22, 0, 0, 0) == []
    assert g.get_neighbors_of_at_offset(22, 5, 0, 0) == []  # outside hood
    assert g.get_neighbors_of_at_offset(9999, 1, 0, 0) == []  # unknown cell
    # at a non-periodic boundary the offset window is empty
    assert g.get_neighbors_of_at_offset(1, -1, 0, 0) == []


def test_neighbors_of_at_offset_refined():
    g = (
        Grid(cell_data={"v": jnp.float32})
        .set_initial_length((2, 2, 1))
        .set_maximum_refinement_level(1)
        .initialize(mesh_of(2))
    )
    g.refine_completely(2)
    g.stop_refining()
    # cell 1's +x window is covered by the 8 children of refined cell 2
    at = g.get_neighbors_of_at_offset(1, 1, 0, 0)
    assert len(at) == 8
    assert {off[0] for _, off in at} <= {2, 3}  # all in the +x window
    # the inverse view: a child of 2 sees coarse cell 1 at EVERY window
    # it covers (the reference's index matching returns it per offset)
    g2 = (
        Grid(cell_data={"v": jnp.float32})
        .set_initial_length((2, 2, 1))
        .set_maximum_refinement_level(1)
        .initialize(mesh_of(2))
    )
    g2.refine_completely(2)
    g2.stop_refining()
    kids = g2.mapping.get_all_children(np.uint64(2))
    # kids[0] at the -x face corner: cell 1 covers its (-1,0,0) and
    # (-1,1,0) windows
    for w in ((-1, 0, 0), (-1, 1, 0)):
        at = g2.get_neighbors_of_at_offset(int(kids[0]), *w)
        assert 1 in [n for n, _ in at], w
    assert all(n in g.get_cells() for n, _ in at)


def test_remote_neighbor_queries():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((8, 1, 1)).initialize(
        mesh_of(4), partition="block"
    )
    # block partition: cells 1-2 on dev0, 3-4 on dev1, ...
    assert list(g.get_remote_neighbors_of(2, sorted=True)) == [3]
    assert list(g.get_remote_neighbors_to(2, sorted=True)) == [3]
    assert len(g.get_remote_neighbors_of(1)) == 0  # inner cell
    assert len(g.get_remote_neighbors_of(9999)) == 0  # unknown cell


def test_find_cells_box():
    g = (
        Grid(cell_data={"v": jnp.float32})
        .set_initial_length((2, 2, 1))
        .set_maximum_refinement_level(1)
        .initialize(mesh_of(2))
    )
    g.refine_completely(1)
    g.stop_refining()
    # index space is 4x4x2; the full box finds every leaf cell
    np.testing.assert_array_equal(
        g.find_cells((0, 0, 0), (3, 3, 1)), g.get_cells()
    )
    # level filter: only the 8 children of cell 1
    lvl1 = g.find_cells((0, 0, 0), (3, 3, 1), minimum_refinement_level=1)
    assert len(lvl1) == 8
    # a corner box inside refined region: single smallest cell
    one = g.find_cells((0, 0, 0), (0, 0, 0), minimum_refinement_level=1)
    assert len(one) == 1
    # the same corner unfiltered also matches only that child (cell 1
    # was refined away)
    np.testing.assert_array_equal(g.find_cells((0, 0, 0), (0, 0, 0)), one)
    with pytest.raises(ValueError):
        g.find_cells((2, 0, 0), (1, 0, 0))
    with pytest.raises(ValueError):
        g.find_cells((0, 0, 0), (1, 1, 1), 1, 0)


def test_process_and_locality():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((4, 4, 1)).initialize(mesh_of(4))
    for c in [1, 8, 16]:
        d = g.get_process(c)
        assert 0 <= d < 4
        assert g.is_local(c, d)


def test_halo_exchange_moves_data():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((8, 1, 1)).initialize(
        mesh_of(4), partition="block"
    )
    ids = np.arange(1, 9, dtype=np.uint64)
    g.set("v", ids, ids.astype(np.float32))
    g.update_copies_of_remote_neighbors()
    # check ghost rows directly: each device's ghost copies must hold
    # the owner's value
    host = np.asarray(g.data["v"])
    for d in range(4):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host[d, g.plan.L + r] == float(cid), (d, cid)


def test_split_phase_exchange():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((8, 1, 1)).initialize(
        mesh_of(4), partition="block"
    )
    ids = np.arange(1, 9, dtype=np.uint64)
    g.set("v", ids, (10 * ids).astype(np.float32))
    g.start_remote_neighbor_copy_updates()
    g.wait_remote_neighbor_copy_update_receives()
    g.wait_remote_neighbor_copy_update_sends()
    host = np.asarray(g.data["v"])
    for d in range(4):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host[d, g.plan.L + r] == 10.0 * float(cid)


def test_split_phase_interleaved_writes_survive():
    """Writes to an exchanged field between start and wait must not be
    reverted by wait: the reference's receives only ever write ghost
    (remote_neighbors) copies (dccrg.hpp:10726-10935)."""
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((8, 1, 1)).initialize(
        mesh_of(4), partition="block"
    )
    ids = np.arange(1, 9, dtype=np.uint64)
    g.set("v", ids, (10 * ids).astype(np.float32))
    g.start_remote_neighbor_copy_updates()
    # interleaved compute: overwrite every local cell's value
    g.set("v", ids, (100 * ids).astype(np.float32))
    g.wait_remote_neighbor_copy_updates()
    host = np.asarray(g.data["v"])
    # local rows keep the interleaved write...
    for cid in ids:
        assert float(g.get("v", cid)) == 100.0 * float(cid)
    # ...while ghost rows hold the values captured at start time
    for d in range(4):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host[d, g.plan.L + r] == 10.0 * float(cid)


def test_split_phase_double_start_raises():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((8, 1, 1)).initialize(
        mesh_of(4), partition="block"
    )
    g.start_remote_neighbor_copy_updates()
    with pytest.raises(RuntimeError):
        g.start_remote_neighbor_copy_updates()
    g.wait_remote_neighbor_copy_updates()
    # distinct neighborhoods may be in flight concurrently
    g.add_neighborhood(9, [[1, 0, 0]])
    g.start_remote_neighbor_copy_updates()
    g.start_remote_neighbor_copy_updates(neighborhood_id=9)
    g.wait_remote_neighbor_copy_updates(neighborhood_id=9)
    g.wait_remote_neighbor_copy_updates()


def test_split_phase_stale_after_structure_change():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((8, 1, 1)).initialize(
        mesh_of(4), partition="block"
    )
    g.start_remote_neighbor_copy_updates()
    g.refine_completely(1)
    g.stop_refining()
    with pytest.raises(RuntimeError):
        g.wait_remote_neighbor_copy_updates()


def test_transfer_accounting():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((8, 1, 1)).initialize(
        mesh_of(4), partition="block"
    )
    # 1-D chain of 4 blocks of 2: 3 interfaces, each sends 1 cell each way
    assert g.get_number_of_update_send_cells() == 6
    assert g.get_number_of_update_receive_cells() == 6


def test_user_neighborhood():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((6, 1, 1)).set_periodic(
        True, False, False
    ).initialize(mesh_of(2))
    assert g.add_neighborhood(7, [[1, 0, 0]])
    assert not g.add_neighborhood(7, [[1, 0, 0]])  # duplicate id
    nbrs = g.get_neighbors_of(3, neighborhood_id=7)
    assert nbrs == [(4, (1, 0, 0))]
    # asymmetric hood: neighbors_to is the inverse
    tos = g.get_neighbors_to(3, neighborhood_id=7)
    assert tos == [(2, (-1, 0, 0))]
    with pytest.raises(ValueError):
        g.add_neighborhood(8, [[0, 0, 0]])
    g.remove_neighborhood(7)
    with pytest.raises(KeyError):
        g.get_neighbors_of(3, neighborhood_id=7)


# ---------------------------------------------------------------------
# game of life end-to-end (examples/simple_game_of_life.cpp)

def test_blinker_oscillates():
    gol = GameOfLife(mesh=mesh_of(8))
    vertical = [gol_id(4, 3), gol_id(4, 4), gol_id(4, 5)]
    horizontal = [gol_id(3, 4), gol_id(4, 4), gol_id(5, 4)]
    gol.set_alive(vertical)
    for turn in range(6):
        gol.step()
        expect = horizontal if turn % 2 == 0 else vertical
        np.testing.assert_array_equal(np.sort(gol.alive_cells()), np.sort(expect)), turn


def test_block_still_life():
    gol = GameOfLife(mesh=mesh_of(8))
    block = [gol_id(1, 1), gol_id(2, 1), gol_id(1, 2), gol_id(2, 2)]
    gol.set_alive(block)
    for _ in range(4):
        gol.step()
        np.testing.assert_array_equal(np.sort(gol.alive_cells()), np.sort(block))


def test_glider_on_periodic_grid():
    gol = GameOfLife(length=(8, 8, 1), periodic=(True, True, False), mesh=mesh_of(8))
    glider = [gol_id(1, 0, 8), gol_id(2, 1, 8), gol_id(0, 2, 8), gol_id(1, 2, 8), gol_id(2, 2, 8)]
    gol.set_alive(glider)
    pop = []
    for _ in range(32):  # 8*4 steps: glider returns to start on 8x8 torus
        gol.step()
        pop.append(len(gol.alive_cells()))
    assert all(p == 5 for p in pop)
    np.testing.assert_array_equal(np.sort(gol.alive_cells()), np.sort(glider))


def test_refined_blinker_far_refinement():
    """GoL on a refined grid (tests/game_of_life/refined.cpp): refining
    cells far from the pattern must not disturb the oscillator."""
    gol = GameOfLife(mesh=mesh_of(4), max_refinement_level=1)
    vertical = [gol_id(4, 3), gol_id(4, 4), gol_id(4, 5)]
    horizontal = [gol_id(3, 4), gol_id(4, 4), gol_id(5, 4)]
    gol.set_alive(vertical)
    # refine the far corner (cells at x>=8, y>=8 are >1 cell away)
    gol.refine([gol_id(9, 9), gol_id(8, 9), gol_id(9, 8)])
    lvl = gol.grid.mapping.get_refinement_level(gol.grid.get_cells())
    assert lvl.max() == 1
    for turn in range(4):
        gol.step()
        expect = horizontal if turn % 2 == 0 else vertical
        np.testing.assert_array_equal(np.sort(gol.alive_cells()), np.sort(expect))


def test_refined_gol_device_invariance():
    """Refined-grid GoL must evolve identically on 1 vs 8 devices
    (tests/README:5-6)."""
    out = []
    for n in (1, 8):
        gol = GameOfLife(length=(6, 6, 1), mesh=mesh_of(n), max_refinement_level=1)
        gol.set_alive([1 + 1 + 1 * 6, 1 + 2 + 1 * 6, 1 + 3 + 1 * 6])
        gol.refine([1, 36])
        for _ in range(4):
            gol.step()
        out.append(np.sort(gol.alive_cells()))
    np.testing.assert_array_equal(out[0], out[1])


@pytest.mark.parametrize("partition", ["block", "morton", "hilbert"])
def test_device_count_invariance(partition, rng):
    """Same results on 1 and 8 devices for random initial states (the
    reference's any-process-count requirement, tests/README:5-6)."""
    init = rng.random((10, 10)) < 0.3
    ids = np.array(
        [gol_id(x, y) for x in range(10) for y in range(10) if init[x, y]], dtype=np.uint64
    )
    results = []
    for n in (1, 8):
        gol = GameOfLife(mesh=mesh_of(n), partition=partition)
        gol.set_alive(ids)
        for _ in range(5):
            gol.step()
        results.append(np.sort(gol.alive_cells()))
    np.testing.assert_array_equal(results[0], results[1])


def test_transfer_predicate_receiver_dependent():
    """Per-peer payload selection (the reference's 5-arg
    get_mpi_datatype, dccrg_get_cell_datatype.hpp:48-213): field 'a'
    is withheld from odd-numbered receivers while 'b' flows everywhere."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dev",))
    g = (Grid(cell_data={"a": jnp.float32, "b": jnp.float32})
         .set_initial_length((8, 2, 1))
         .initialize(mesh))
    cells = g.plan.cells
    g.set_many(cells, {"a": cells.astype(np.float32),
                       "b": -cells.astype(np.float32)})
    g.set_transfer_predicate(
        "a", lambda ids, sender, receiver, hood: np.full(len(ids), receiver % 2 == 0)
    )
    g.update_copies_of_remote_neighbors()
    host_a = np.asarray(g.data["a"])
    host_b = np.asarray(g.data["b"])
    checked_blocked = checked_passed = 0
    for d in range(4):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host_b[d, g.plan.L + r] == -float(cid)  # b always flows
            if d % 2 == 0:
                assert host_a[d, g.plan.L + r] == float(cid)
                checked_passed += 1
            else:
                assert host_a[d, g.plan.L + r] == 0.0  # withheld
                checked_blocked += 1
    assert checked_blocked and checked_passed
    # split-phase path honors the same tables
    g.set("a", cells, 2 * cells.astype(np.float32))
    g.start_remote_neighbor_copy_updates(fields=["a"])
    g.wait_remote_neighbor_copy_updates()
    host_a = np.asarray(g.data["a"])
    for d in range(0, 4, 2):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host_a[d, g.plan.L + r] == 2 * float(cid)
    # clearing restores full exchange
    g.set_transfer_predicate("a", None)
    g.update_copies_of_remote_neighbors()
    host_a = np.asarray(g.data["a"])
    for d in range(4):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host_a[d, g.plan.L + r] == 2 * float(cid)


def test_transfer_predicate_in_fused_loop():
    """run_steps must honor transfer predicates for exchanged fields."""
    mesh = Mesh(np.array(jax.devices()[:2]), ("dev",))
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((4, 1, 1))
         .initialize(mesh))
    cells = g.plan.cells
    g.set("v", cells, cells.astype(np.float32))
    g.set_transfer_predicate(
        "v", lambda ids, s, r, h: np.zeros(len(ids), dtype=bool)
    )

    def kernel(cell, nbr, offs, mask, *extra):
        # sum of neighbors: with the predicate blocking all transfers,
        # ghost rows stay zero, so edge cells see only local neighbors
        return {"v": jnp.sum(jnp.where(mask, nbr["v"], 0.0), axis=1)}

    g.run_steps(kernel, ["v"], ["v"], 1)
    got = g.get("v", cells)
    # cell 2 (pos 1 on dev 0): neighbors 1 and 3; 3 is remote and
    # blocked -> sees only 1
    assert got[1] == 1.0
    assert got[2] == 4.0  # cell 3 sees only local 4
    # changing the predicate must invalidate the compiled loop too
    g.set("v", cells, cells.astype(np.float32))
    g.set_transfer_predicate("v", None)
    g.run_steps(kernel, ["v"], ["v"], 1)
    got = g.get("v", cells)
    assert got[1] == 1.0 + 3.0  # remote neighbor flows again
    assert got[2] == 2.0 + 4.0


def test_roll_gather_matches_table_gather(monkeypatch):
    """The roll-decomposed neighbor gather (TPU default) must equal
    the table gather on uniform and refined plans, through both
    apply_stencil and the fused run_steps loop."""
    def build():
        mesh = Mesh(np.array(jax.devices()[:2]), ("dev",))
        g = (Grid(cell_data={"v": jnp.float32})
             .set_initial_length((16, 16, 4))
             .set_periodic(True, False, False)
             .set_maximum_refinement_level(1)
             .initialize(mesh))
        g.refine_completely(1)
        g.stop_refining()
        cells = g.plan.cells
        rng = np.random.default_rng(5)
        g.set("v", cells, rng.random(len(cells)).astype(np.float32))
        g.update_copies_of_remote_neighbors()
        return g

    def kernel(cell, nbr, offs, mask, *e):
        return {"v": cell["v"] + 0.25 * jnp.sum(
            jnp.where(mask, nbr["v"] * (1 + offs[..., 0]), 0.0), axis=1)}

    results = {}
    for mode in ("1", "0"):
        monkeypatch.setenv("DCCRG_ROLL_STENCIL", mode)
        g = build()
        if mode == "1":
            hood = g.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
            assert hood.roll_plan(g.plan.L) is not None
        g.apply_stencil(kernel, ["v"], ["v"])
        one = g.get("v", g.plan.cells).copy()
        g.run_steps(kernel, ["v"], ["v"], 2)
        results[mode] = (one, g.get("v", g.plan.cells))
    np.testing.assert_allclose(results["1"][0], results["0"][0], rtol=1e-6)
    np.testing.assert_allclose(results["1"][1], results["0"][1], rtol=1e-6)


def test_gol_fused_run_matches_steps():
    """N fused generations == N single steps, bit for bit."""
    from dccrg_tpu.models.game_of_life import GameOfLife

    mesh = Mesh(np.array(jax.devices()[:4]), ("dev",))

    def glider(gol):
        mp = gol.grid.mapping
        for x, y in ((1, 0), (2, 1), (0, 2), (1, 2), (2, 2)):
            gol.set_alive([mp.get_cell_from_indices(
                np.array([x, y, 0], dtype=np.uint64), 0)])

    a = GameOfLife(length=(12, 12, 1), periodic=(True, True, False), mesh=mesh)
    glider(a)
    for _ in range(6):
        a.step()
    b = GameOfLife(length=(12, 12, 1), periodic=(True, True, False), mesh=mesh)
    glider(b)
    b.run(6)
    np.testing.assert_array_equal(np.sort(a.alive_cells()),
                                  np.sort(b.alive_cells()))


def test_peer_exchange_buffers_compact():
    """The per-peer ppermute exchange moves far fewer rows than the
    dense all_to_all buffer for compact partitions (block: only the
    +-1 device offsets -> 4x at 8 devices)."""
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((32, 32, 32))
         .set_periodic(True, True, True)
         .initialize(Mesh(np.array(jax.devices()[:8]), ("dev",)),
                     partition="block"))
    deltas = g._peer_deltas(DEFAULT_NEIGHBORHOOD_ID)
    assert deltas == (1, 7)  # +-1 neighbors (mod 8)
    sends, _ = g._pair_tables_device(DEFAULT_NEIGHBORHOOD_ID, ("v",))
    hood = g.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    dense_rows = g.n_dev * hood.send_rows.shape[2]
    peer_rows = sum(t.shape[1] for t in sends)
    assert dense_rows >= 3 * peer_rows  # ~4x fewer rows on the wire


def test_multi_process_mesh_accepted_with_rank_local_access(monkeypatch):
    """A mesh containing another process's devices initializes (the
    plan is replicated structure, computed identically on every
    process — dccrg.hpp:7311), but host get/set become rank-local: on
    a process that owns NO mesh devices every cell is foreign.
    Deeper multi-process behavior is covered by
    tests/test_multiprocess.py's faked splits."""
    monkeypatch.setattr(jax, "process_index", lambda backend=None: 99)
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((4, 4, 4))
         .initialize())
    assert g._multiproc
    with pytest.raises(KeyError, match="process-local"):
        g.get("v", g.plan.cells[:2])


def test_transfer_predicate_requires_initialize():
    g = Grid(cell_data={"v": jnp.float32}).set_initial_length((4, 4, 4))
    with pytest.raises(RuntimeError, match="initialize"):
        g.set_transfer_predicate("v", lambda ids, s, r, h: ids >= 0)


def test_device_row_ids_matches_plan():
    """device_row_ids mirrors local_ids/ghost_ids row layout exactly
    (local rows [0, n_local), ghosts at [L, L+n_ghost), -1 elsewhere)."""
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((8, 8, 4))
         .set_periodic(True, True, False)
         .initialize(Mesh(np.array(jax.devices()[:8]), ("dev",)),
                     partition="morton"))
    arr = np.asarray(g.device_row_ids())
    expect = np.full_like(arr, -1)
    for d in range(g.n_dev):
        nl = int(g.plan.n_local[d])
        expect[d, :nl] = g.plan.local_ids[d].astype(np.int64) - 1
        ng = len(g.plan.ghost_ids[d])
        expect[d, g.plan.L:g.plan.L + ng] = (
            g.plan.ghost_ids[d].astype(np.int64) - 1)
    np.testing.assert_array_equal(arr, expect)
    # single-device closed-form grid: synthesized from iota
    g1 = (Grid(cell_data={"v": jnp.float32})
          .set_initial_length((4, 4, 4))
          .initialize(Mesh(np.array(jax.devices()[:1]), ("dev",))))
    a1 = np.asarray(g1.device_row_ids())
    assert a1.shape == (1, g1.plan.R)
    np.testing.assert_array_equal(a1[0, :64], np.arange(64))
    assert (a1[0, 64:] == -1).all()


def test_cut_partition_beats_morton_halo_traffic():
    """The connectivity-aware balance (VERDICT r3 item 6): on a
    refined grid, method='cut' must move measurably fewer halo bytes
    per update than morton, at bounded imbalance."""
    from dccrg_tpu.utils.profiling import halo_bytes_per_update

    results = {}
    for method in ("morton", "cut"):
        g = (Grid(cell_data={"v": jnp.float32})
             .set_initial_length((16, 16, 4))
             .set_maximum_refinement_level(1)
             .set_neighborhood_length(1)
             .initialize(Mesh(np.array(jax.devices()[:8]), ("dev",)),
                         partition="morton"))
        cells = g.plan.cells
        idx = g.mapping.get_indices(cells)
        r = np.linalg.norm(idx - np.array([16, 16, 4]), axis=1)
        for c in cells[r < 8]:
            g.refine_completely(c)
        g.stop_refining()
        g._lb_method = method
        g.balance_load()
        results[method] = halo_bytes_per_update(g)
        loads = np.bincount(g.plan.owner, minlength=8)
        assert loads.max() <= 1.25 * len(g.plan.cells) / 8
    assert results["cut"] < 0.92 * results["morton"], results


def test_cut_partition_beats_rcb_on_anisotropic_grid():
    """VERDICT r4 item 9: with the KL swap pass, 'cut' must not move
    more halo bytes than plain RCB even on an anisotropic (stretched)
    grid with refinement, where RCB's index-space bisection is already
    strong."""
    from dccrg_tpu.utils.profiling import halo_bytes_per_update

    results = {}
    for method in ("rcb", "cut"):
        g = (Grid(cell_data={"v": jnp.float32})
             .set_initial_length((32, 8, 2))
             .set_maximum_refinement_level(1)
             .set_neighborhood_length(1)
             .initialize(Mesh(np.array(jax.devices()[:8]), ("dev",)),
                         partition="morton"))
        cells = g.plan.cells
        idx = g.mapping.get_indices(cells)
        r = np.linalg.norm((idx - np.array([8, 8, 2]))
                           / np.array([4.0, 1.0, 1.0]), axis=1)
        for c in cells[r < 6]:
            g.refine_completely(c)
        g.stop_refining()
        g._lb_method = method
        g.balance_load()
        results[method] = halo_bytes_per_update(g)
        loads = np.bincount(g.plan.owner, minlength=8)
        assert loads.max() <= 1.25 * len(g.plan.cells) / 8
    assert results["cut"] <= results["rcb"], results
