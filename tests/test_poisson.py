"""Poisson solver tests (the reference's tests/poisson suite):
convergence against analytic solutions in 1-D/2-D/3-D, comparison with
a serial reference solve, and the multi-field transfer selection."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu.dense import dense_mesh
from dccrg_tpu.models.poisson import DensePoissonSolver, PoissonSolver


def mesh1(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def discrete_rel_error(got, want):
    return float(np.linalg.norm(got - want) / np.linalg.norm(want))


def test_1d_periodic_analytic():
    n = 32
    s = PoissonSolver((n, 1, 1), mesh=mesh1(4), periodic=(True, False, False))
    cells = s.grid.get_cells()
    x = s.grid.geometry.get_center(cells)[:, 0] / n  # NoGeometry: unit cells
    u = np.sin(2 * np.pi * x)
    # the DISCRETE operator's eigenvalue makes the test exact up to CG
    # tolerance: A u = lam u for the unit-cell discrete Laplacian
    lam = -(2 - 2 * np.cos(2 * np.pi / n))
    rhs = lam * u
    s.set_rhs(rhs.astype(np.float32))
    info = s.solve(rtol=1e-6, max_iterations=500)
    got = s.solution()
    got -= got.mean()
    assert discrete_rel_error(got, u - u.mean()) < 1e-3, info


def test_2d_matches_serial_reference():
    """Multi-device solve equals the single-device (serial) solve — the
    reference's reference_poisson_solve comparison strategy."""
    n = 8
    rng = np.random.default_rng(3)
    rhs = rng.standard_normal(n * n).astype(np.float32)
    rhs -= rhs.mean()
    sols = []
    for ndev in (1, 8):
        s = PoissonSolver((n, n, 1), mesh=mesh1(ndev), periodic=(True, True, False))
        s.set_rhs(rhs)
        info = s.solve(rtol=1e-6, max_iterations=1000)
        x = s.solution()
        sols.append(x - x.mean())
    assert discrete_rel_error(sols[1], sols[0]) < 1e-3


def test_residual_actually_small():
    n = 8
    s = PoissonSolver((n, n, n), mesh=mesh1(8))
    rng = np.random.default_rng(0)
    rhs = rng.standard_normal(n**3).astype(np.float32)
    s.set_rhs(rhs)
    info = s.solve(rtol=1e-5, max_iterations=2000)
    # verify A x = rhs - mean(rhs) by recomputing the matvec
    g = s.grid
    g.data["p0"] = g.data["solution"]
    s._exchange_p(["p0"])
    s._apply(transpose=False)
    cells = g.get_cells()
    Ax = g.get("Ap0", cells)
    want = rhs - rhs.mean()
    assert np.linalg.norm(Ax - want) / np.linalg.norm(want) < 1e-3, info


def test_dirichlet_boundary_cells():
    """Cells neither solved nor skipped are boundary cells whose
    solution is fixed Dirichlet data (poisson_solve.hpp:236-239,
    reference tests/poisson/poisson2d_boundary.cpp). The factor scheme
    is exact for linear solutions."""
    n = 8
    s = PoissonSolver((n, 1, 1), mesh=mesh1(2), periodic=(False, False, False))
    cells = s.grid.get_cells()
    x = s.grid.geometry.get_center(cells)[:, 0]
    interior = cells[(x > 1) & (x < n - 1)]
    boundary = cells[(x < 1) | (x > n - 1)]
    # u = 3x + 1: zero rhs, boundary holds the exact values
    s.grid.set("solution", boundary,
               (3 * s.grid.geometry.get_center(boundary)[:, 0] + 1).astype(np.float32))
    s.set_rhs(np.zeros(len(cells), dtype=np.float32))
    info = s.solve(rtol=1e-8, max_iterations=500, cells_to_solve=interior)
    got = s.solution()
    np.testing.assert_allclose(got, 3 * x + 1, rtol=1e-4, atol=1e-3, err_msg=str(info))


def test_skip_cells_decouple():
    """Skipped cells act as missing neighbors and keep their data
    (poisson_solve.hpp:229-235, the reference's skip-cells variant)."""
    n = 9
    s = PoissonSolver((n, 1, 1), mesh=mesh1(2), periodic=(False, False, False))
    cells = s.grid.get_cells()
    x = s.grid.geometry.get_center(cells)[:, 0]
    mid = cells[len(cells) // 2]
    s.grid.set("solution", np.array([mid]), np.array([123.0], np.float32))
    solve = cells[cells != mid]
    rng = np.random.default_rng(5)
    rhs = rng.standard_normal(len(cells)).astype(np.float32)
    # each decoupled half is a pure-Neumann (singular) system: make the
    # rhs compatible per half so a solution exists
    half_l = x < x[len(cells) // 2]
    half_r = x > x[len(cells) // 2]
    rhs[half_l] -= rhs[half_l].mean()
    rhs[half_r] -= rhs[half_r].mean()
    s.set_rhs(rhs)
    info = s.solve(rtol=1e-6, max_iterations=500,
                   cells_to_solve=solve, cells_to_skip=[mid])
    # the skipped cell is untouched
    assert float(s.grid.get("solution", np.uint64(mid))) == 123.0
    # and fully decoupled: its rhs never influenced either half; check
    # by verifying the residual of the solved system directly
    g = s.grid
    g.data["p0"] = g.data["solution"]
    s._exchange_p(["p0"])
    s._apply(transpose=False)
    Ax = g.get("Ap0", solve)
    r = Ax - rhs[cells != mid]
    # pure-Neumann halves: each half's rhs mean is a nullspace offset;
    # remove per-half means before comparing
    left = s.grid.geometry.get_center(solve)[:, 0] < x[len(cells) // 2]
    for m in (left, ~left):
        r[m] -= r[m].mean()
    assert np.linalg.norm(r) / max(np.linalg.norm(rhs), 1e-9) < 1e-3, info


def test_amr_linear_exact():
    """AMR grid: factors across coarse-fine faces (f/4 per finer
    neighbor, poisson_solve.hpp:332-338) reproduce a linear solution
    exactly (reference tests/poisson refinement variants)."""
    s = PoissonSolver((4, 1, 1), mesh=mesh1(2), periodic=(False, False, False),
                      max_refinement_level=1)
    s.grid.refine_completely(2)
    s.grid.stop_refining()
    cells = s.grid.get_cells()
    x = s.grid.geometry.get_center(cells)[:, 0]
    exact = (2.0 * x - 1.0).astype(np.float32)
    lo, hi = x.min(), x.max()
    boundary = cells[(x == lo) | (x == hi)]
    interior = cells[(x != lo) & (x != hi)]
    s.grid.set("solution", boundary, exact[(x == lo) | (x == hi)])
    s.set_rhs(np.zeros(len(cells), dtype=np.float32))
    info = s.solve(rtol=1e-10, max_iterations=500, cells_to_solve=interior)
    np.testing.assert_allclose(s.solution(), exact, rtol=1e-3, atol=2e-3, err_msg=str(info))


def test_stretched_linear_exact():
    """Stretched-Cartesian geometry feeds the factors through
    geometry.get_length (reference tests/poisson stretched variant)."""
    coords_x = [0.0, 0.5, 1.5, 3.0, 5.0, 7.5]
    from dccrg_tpu.grid import Grid
    from dccrg_tpu.models.poisson import POISSON_FIELDS

    g = (
        Grid(cell_data=dict(POISSON_FIELDS))
        .set_initial_length((5, 1, 1))
        .set_neighborhood_length(1)
        .set_geometry("stretched", coordinates=[coords_x, [0.0, 1.0], [0.0, 1.0]])
        .initialize(mesh1(2))
    )
    s = PoissonSolver(grid=g)
    cells = g.get_cells()
    x = g.geometry.get_center(cells)[:, 0]
    exact = (0.5 * x + 2.0).astype(np.float32)
    boundary = cells[(x == x.min()) | (x == x.max())]
    interior = cells[(x != x.min()) & (x != x.max())]
    g.set("solution", boundary, exact[(x == x.min()) | (x == x.max())])
    s.set_rhs(np.zeros(len(cells), dtype=np.float32))
    info = s.solve(rtol=1e-10, max_iterations=200, cells_to_solve=interior)
    np.testing.assert_allclose(s.solution(), exact, rtol=1e-4, atol=1e-3, err_msg=str(info))


def test_dense_poisson_3d():
    n = 32
    mesh = dense_mesh(jax.devices()[:8], (2, 2, 2))
    s = DensePoissonSolver((n, n, n), mesh=mesh)
    x = (np.arange(n) + 0.5) / n
    u = (
        np.sin(2 * np.pi * x)[:, None, None]
        * np.sin(2 * np.pi * x)[None, :, None]
        * np.ones((1, 1, n))
    )
    rhs = -2 * (2 * np.pi) ** 2 * u
    sol, info = s.solve(jnp.asarray(rhs, jnp.float32), rtol=1e-6, max_iterations=800)
    got = np.array(sol)
    got -= got.mean()
    # discretization error dominates at n=32
    err = discrete_rel_error(got, u - u.mean())
    assert err < 0.02, (err, info)


def test_dense_matches_general_small():
    """Dense and general paths agree on the same problem."""
    n = 8
    rng = np.random.default_rng(1)
    rhs3 = rng.standard_normal((n, n, n)).astype(np.float32)
    rhs3 -= rhs3.mean()

    dense_sol, _ = DensePoissonSolver(
        (n, n, n), mesh=dense_mesh(jax.devices()[:1], (1, 1, 1))
    ).solve(jnp.asarray(rhs3), rtol=1e-6, max_iterations=2000)

    s = PoissonSolver((n, n, n), mesh=mesh1(1))
    # general grid orders cells by id: x fastest -> index (i,j,k) = id-1
    cells = s.grid.get_cells()
    idx = s.grid.mapping.get_indices(cells).astype(np.int64)
    rhs_flat = rhs3[idx[:, 0], idx[:, 1], idx[:, 2]]
    # general path uses unit cells (NoGeometry): rescale rhs by dx^-2
    # equivalence: A_unit u = dx^2 * A_dx u with dx = 1/n
    s.set_rhs(rhs_flat * np.float32((1.0 / n) ** 2))
    s.solve(rtol=1e-6, max_iterations=2000)
    gen = s.solution()
    dense_at = np.asarray(dense_sol)[idx[:, 0], idx[:, 1], idx[:, 2]]
    gen -= gen.mean()
    dense_at -= dense_at.mean()
    assert discrete_rel_error(gen, dense_at) < 1e-3


def test_f64_parity_mode():
    """The reference solver family is double precision
    (tests/poisson/reference_poisson_solve.hpp); poisson_fields(f64)
    is the parity mode, and the measured gap documents the f32 error
    budget: f64 converges ~6 orders of magnitude deeper."""
    import jax.numpy as jnp
    from dccrg_tpu.models.poisson import PoissonSolver

    def run(dtype):
        s = PoissonSolver(length=(16, 16, 1), mesh=mesh1(4), dtype=dtype,
                          periodic=(True, True, True))
        cells = s.grid.get_cells()
        centers = s.grid.geometry.get_center(cells)
        rhs = np.sin(2 * np.pi * centers[:, 0] / 16) * np.sin(
            2 * np.pi * centers[:, 1] / 16
        )
        s.set_rhs(rhs)
        s.solve(rtol=1e-12, max_iterations=400)
        sol = s.grid.get("solution", cells).astype(np.float64)
        # the rhs is a discrete eigenfunction: the 5-point Laplacian's
        # eigenvalue at mode k=1 on unit cells is 2(cos(2*pi/16)-1) per
        # dimension, so the exact discrete solution is rhs / eigenvalue
        lam = 2 * (np.cos(2 * np.pi / 16) - 1) * 2
        exact = rhs / lam
        sol -= sol.mean()
        exact -= exact.mean()
        return float(np.abs(sol - exact).max() / np.abs(exact).max())

    err64 = run(jnp.float64)
    err32 = run(jnp.float32)
    # f64 resolves the discrete solution to near machine precision,
    # f32 bottoms out around its rounding floor — the error budget a
    # TPU (f32) run should expect
    assert err64 < 1e-9, err64
    assert err64 < err32, (err64, err32)
    assert err32 < 1e-4, err32


def test_fused_solve_matches_host_loop():
    """The single-program lax.while_loop solve must walk the same
    Krylov trajectory as the host-driven loop (same ops, same order)."""
    import jax.numpy as jnp
    from dccrg_tpu.models.poisson import PoissonSolver

    def make():
        s = PoissonSolver(length=(8, 8, 4), mesh=mesh1(4),
                          periodic=(True, False, False),
                          max_refinement_level=1)
        g = s.grid
        g.refine_completely(1)
        g.stop_refining()
        cells = g.get_cells()
        centers = g.geometry.get_center(cells)
        rng = np.random.default_rng(0)
        s.set_rhs(np.sin(centers[:, 0]) + 0.1 * rng.random(len(cells)))
        # Dirichlet boundary: first level-0 plane
        solve = cells[centers[:, 1] > 1.5]
        return s, solve

    s1, solve1 = make()
    out1 = s1.solve(rtol=1e-6, max_iterations=60, cells_to_solve=solve1,
                    fused=True)
    s2, solve2 = make()
    out2 = s2.solve(rtol=1e-6, max_iterations=60, cells_to_solve=solve2,
                    fused=False)
    assert out1["iterations"] == out2["iterations"]
    # f32 reduction orders differ between the fused and host programs
    np.testing.assert_allclose(out1["residual"], out2["residual"],
                               rtol=5e-2, atol=1e-10)
    np.testing.assert_allclose(s1.solution(), s2.solution(),
                               rtol=5e-4, atol=5e-6)
