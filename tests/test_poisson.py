"""Poisson solver tests (the reference's tests/poisson suite):
convergence against analytic solutions in 1-D/2-D/3-D, comparison with
a serial reference solve, and the multi-field transfer selection."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu.dense import dense_mesh
from dccrg_tpu.models.poisson import DensePoissonSolver, PoissonSolver


def mesh1(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def discrete_rel_error(got, want):
    return float(np.linalg.norm(got - want) / np.linalg.norm(want))


def test_1d_periodic_analytic():
    n = 32
    s = PoissonSolver((n, 1, 1), mesh=mesh1(4), periodic=(True, False, False))
    cells = s.grid.get_cells()
    x = s.grid.geometry.get_center(cells)[:, 0] / n  # NoGeometry: unit cells
    u = np.sin(2 * np.pi * x)
    # the DISCRETE operator's eigenvalue makes the test exact up to CG
    # tolerance: A u = lam u for the unit-cell discrete Laplacian
    lam = -(2 - 2 * np.cos(2 * np.pi / n))
    rhs = lam * u
    s.set_rhs(rhs.astype(np.float32))
    info = s.solve(rtol=1e-6, max_iterations=500)
    got = s.solution()
    got -= got.mean()
    assert discrete_rel_error(got, u - u.mean()) < 1e-3, info


def test_2d_matches_serial_reference():
    """Multi-device solve equals the single-device (serial) solve — the
    reference's reference_poisson_solve comparison strategy."""
    n = 8
    rng = np.random.default_rng(3)
    rhs = rng.standard_normal(n * n).astype(np.float32)
    rhs -= rhs.mean()
    sols = []
    for ndev in (1, 8):
        s = PoissonSolver((n, n, 1), mesh=mesh1(ndev), periodic=(True, True, False))
        s.set_rhs(rhs)
        info = s.solve(rtol=1e-6, max_iterations=1000)
        x = s.solution()
        sols.append(x - x.mean())
    assert discrete_rel_error(sols[1], sols[0]) < 1e-3


def test_residual_actually_small():
    n = 8
    s = PoissonSolver((n, n, n), mesh=mesh1(8))
    rng = np.random.default_rng(0)
    rhs = rng.standard_normal(n**3).astype(np.float32)
    s.set_rhs(rhs)
    info = s.solve(rtol=1e-5, max_iterations=2000)
    # verify A x = rhs - mean(rhs) by recomputing the matvec
    g = s.grid
    g.data["p"] = g.data["solution"]
    s._matvec()
    cells = g.get_cells()
    Ax = g.get("Ap", cells)
    want = rhs - rhs.mean()
    assert np.linalg.norm(Ax - want) / np.linalg.norm(want) < 1e-3, info


def test_dense_poisson_3d():
    n = 32
    mesh = dense_mesh(jax.devices()[:8], (2, 2, 2))
    s = DensePoissonSolver((n, n, n), mesh=mesh)
    x = (np.arange(n) + 0.5) / n
    u = (
        np.sin(2 * np.pi * x)[:, None, None]
        * np.sin(2 * np.pi * x)[None, :, None]
        * np.ones((1, 1, n))
    )
    rhs = -2 * (2 * np.pi) ** 2 * u
    sol, info = s.solve(jnp.asarray(rhs, jnp.float32), rtol=1e-6, max_iterations=800)
    got = np.array(sol)
    got -= got.mean()
    # discretization error dominates at n=32
    err = discrete_rel_error(got, u - u.mean())
    assert err < 0.02, (err, info)


def test_dense_matches_general_small():
    """Dense and general paths agree on the same problem."""
    n = 8
    rng = np.random.default_rng(1)
    rhs3 = rng.standard_normal((n, n, n)).astype(np.float32)
    rhs3 -= rhs3.mean()

    dense_sol, _ = DensePoissonSolver(
        (n, n, n), mesh=dense_mesh(jax.devices()[:1], (1, 1, 1))
    ).solve(jnp.asarray(rhs3), rtol=1e-6, max_iterations=2000)

    s = PoissonSolver((n, n, n), mesh=mesh1(1))
    # general grid orders cells by id: x fastest -> index (i,j,k) = id-1
    cells = s.grid.get_cells()
    idx = s.grid.mapping.get_indices(cells).astype(np.int64)
    rhs_flat = rhs3[idx[:, 0], idx[:, 1], idx[:, 2]]
    # general path uses unit cells (NoGeometry): rescale rhs by dx^-2
    # equivalence: A_unit u = dx^2 * A_dx u with dx = 1/n
    s.set_rhs(rhs_flat * np.float32((1.0 / n) ** 2))
    s.solve(rtol=1e-6, max_iterations=2000)
    gen = s.solution()
    dense_at = np.asarray(dense_sol)[idx[:, 0], idx[:, 1], idx[:, 2]]
    gen -= gen.mean()
    dense_at -= dense_at.mean()
    assert discrete_rel_error(gen, dense_at) < 1e-3
