"""Neighbor engine tests.

Cross-checks the vectorized engine against a brute-force geometric
overlap computation on uniform and randomly refined 2:1-balanced grids
(the reference's DEBUG verify_neighbors strategy, dccrg.hpp:12516-12750,
done as an independent reimplementation instead of a recomputation).
"""

import numpy as np
import pytest

from dccrg_tpu import GridTopology, Mapping
from dccrg_tpu.neighbors import (
    NeighborLists,
    StructureError,
    build_neighbor_lists,
    find_neighbors_of,
    make_neighborhood,
    validate_neighborhood,
    verify_tiling,
)


# ---------------------------------------------------------------------
# helpers

def refine_to_valid(mapping, topology, cells, to_refine, hood_len=1):
    """Refine `to_refine` plus whatever induced refinement is needed to
    keep every neighborhood within 1 level (naive fixpoint)."""
    cells = set(int(c) for c in cells)
    queue = list(int(c) for c in to_refine)
    while queue:
        c = queue.pop()
        if c not in cells:
            continue
        lvl = mapping.get_refinement_level(c)
        if lvl >= mapping.max_refinement_level:
            continue
        # refining c: every cell overlapping c's radius-hood window must
        # be at least at c's level
        cells.remove(c)
        kids = mapping.get_all_children(np.uint64(c))
        cells.update(int(k) for k in kids)
        for v in list(cells):
            vl = mapping.get_refinement_level(v)
            if vl < lvl and cells_touch(mapping, topology, c, v, hood_len):
                queue.append(v)
    return np.sort(np.array(sorted(cells), dtype=np.uint64))


def cells_touch(mapping, topology, a, b, hood_len):
    """True if b overlaps any neighborhood window of a."""
    il = mapping.get_index_length().astype(np.int64)
    la = mapping.get_refinement_level(a)
    sa = 1 << (mapping.max_refinement_level - la)
    ia = mapping.get_indices(np.uint64(a)).astype(np.int64)
    lb = mapping.get_refinement_level(b)
    sb = 1 << (mapping.max_refinement_level - lb)
    ib = mapping.get_indices(np.uint64(b)).astype(np.int64)
    lo = ia - hood_len * sa
    hi = ia + (hood_len + 1) * sa  # exclusive
    for d in range(3):
        if topology.is_periodic(d):
            # does [ib, ib+sb) intersect [lo, hi) modulo il?
            if not _periodic_overlap(lo[d], hi[d], ib[d], ib[d] + sb, il[d]):
                return False
        else:
            if ib[d] + sb <= lo[d] or ib[d] >= hi[d]:
                return False
    return True


def _periodic_overlap(lo, hi, blo, bhi, period):
    for shift in (-period, 0, period):
        if blo + shift < hi and bhi + shift > lo:
            return True
    return False


def brute_force_neighbors_of(mapping, topology, cells, cell, hood):
    """All (neighbor, offset) pairs per hood item by direct overlap."""
    il = mapping.get_index_length().astype(np.int64)
    lvl = mapping.get_refinement_level(np.uint64(cell))
    s = 1 << (mapping.max_refinement_level - lvl)
    base = mapping.get_indices(np.uint64(cell)).astype(np.int64)
    out = []
    lv_all = mapping.get_refinement_level(cells)
    sz_all = 1 << (mapping.max_refinement_level - lv_all)
    ix_all = mapping.get_indices(cells).astype(np.int64)
    for it, h in enumerate(hood):
        win = base + np.asarray(h, np.int64) * s
        wrapped = win.copy()
        ok = True
        for d in range(3):
            if topology.is_periodic(d):
                wrapped[d] = wrapped[d] % il[d]
            elif not (0 <= win[d] < il[d]):
                ok = False
        if not ok:
            continue
        for v, vl, vs, vi in zip(cells, lv_all, sz_all, ix_all):
            if all(vi[d] < wrapped[d] + s and vi[d] + vs > wrapped[d] for d in range(3)):
                # logical offset: window offset + position within window
                rel = vi - wrapped
                out.append((it, int(v), tuple(h * s + rel)))
    # the engine collapses exact-duplicate (neighbor, offset) entries
    # (a coarser neighbor covering several windows), keeping the
    # first / lowest item
    seen = {}
    for it, v, off in out:
        seen.setdefault((v, off), (it, v, off))
    return list(seen.values())


def engine_neighbors_of(mapping, topology, cells, cell, hood):
    q = np.array([cell], dtype=np.uint64)
    src, nbr, off, item = find_neighbors_of(mapping, topology, cells, q, hood)
    return [(int(i), int(v), tuple(o)) for i, v, o in zip(item, nbr, off)]


# ---------------------------------------------------------------------
# neighborhood construction

def test_make_neighborhood_faces():
    h = make_neighborhood(0)
    assert h.shape == (6, 3)
    np.testing.assert_array_equal(h[0], [0, 0, -1])
    np.testing.assert_array_equal(h[5], [0, 0, 1])


def test_make_neighborhood_cube():
    h = make_neighborhood(1)
    assert h.shape == (26, 3)
    assert not np.any(np.all(h == 0, axis=1))
    h2 = make_neighborhood(2)
    assert h2.shape == (124, 3)


def test_validate_neighborhood():
    validate_neighborhood([[1, 0, 0], [0, -1, 0]], 1)
    with pytest.raises(ValueError):
        validate_neighborhood([[0, 0, 0]], 1)
    with pytest.raises(ValueError):
        validate_neighborhood([[2, 0, 0]], 1)
    with pytest.raises(ValueError):
        validate_neighborhood([[1, 0, 0], [1, 0, 0]], 1)


# ---------------------------------------------------------------------
# uniform grids

def test_uniform_face_neighbors():
    m = Mapping((4, 4, 4))
    t = GridTopology()
    cells = np.arange(1, 65, dtype=np.uint64)
    hood = make_neighborhood(0)
    # interior cell (1,1,1) -> id 1 + 1 + 4 + 16 = 22
    got = engine_neighbors_of(m, t, cells, 22, hood)
    ids = [v for _, v, _ in got]
    assert ids == [22 - 16, 22 - 4, 22 - 1, 22 + 1, 22 + 4, 22 + 16]
    offs = [o for _, _, o in got]
    assert offs == [(0, 0, -1), (0, -1, 0), (-1, 0, 0), (1, 0, 0), (0, 1, 0), (0, 0, 1)]
    # corner cell 1: only 3 neighbors (+x +y +z)
    got = engine_neighbors_of(m, t, cells, 1, hood)
    assert [v for _, v, _ in got] == [2, 5, 17]


def test_uniform_periodic_wraps():
    m = Mapping((4, 1, 1))
    t = GridTopology((True, False, False))
    cells = np.arange(1, 5, dtype=np.uint64)
    hood = np.array([[-1, 0, 0], [1, 0, 0], [2, 0, 0]])
    got = engine_neighbors_of(m, t, cells, 4, hood)
    # -x: 3; +x wraps to 1; +2x wraps to 2; offsets stay logical
    assert got == [(0, 3, (-1, 0, 0)), (1, 1, (1, 0, 0)), (2, 2, (2, 0, 0))]


def test_one_cell_periodic_grid_sees_itself_26_times():
    m = Mapping((1, 1, 1))
    t = GridTopology((True, True, True))
    cells = np.array([1], dtype=np.uint64)
    got = engine_neighbors_of(m, t, cells, 1, make_neighborhood(1))
    assert len(got) == 26
    assert all(v == 1 for _, v, _ in got)
    assert len(set(o for _, _, o in got)) == 26


def test_uniform_matches_brute_force():
    m = Mapping((4, 3, 2))
    t = GridTopology((True, False, True))
    cells = np.arange(1, 25, dtype=np.uint64)
    hood = make_neighborhood(1)
    for c in [1, 7, 13, 24]:
        got = engine_neighbors_of(m, t, cells, c, hood)
        want = brute_force_neighbors_of(m, t, cells, c, hood)
        assert sorted(got) == sorted(want), f"cell {c}"


# ---------------------------------------------------------------------
# refined grids

def refined_grid(length, max_lvl, refine_ids, periodic=(False, False, False), hood_len=1):
    m = Mapping(length, maximum_refinement_level=max_lvl)
    t = GridTopology(periodic)
    n0 = int(np.prod(np.asarray(length)))
    cells = np.arange(1, n0 + 1, dtype=np.uint64)
    cells = refine_to_valid(m, t, cells, refine_ids, hood_len)
    verify_tiling(m, cells)
    return m, t, cells


def test_refined_corner_matches_brute_force():
    m, t, cells = refined_grid((2, 2, 2), 1, [1])
    hood = make_neighborhood(1)
    for c in cells:
        got = engine_neighbors_of(m, t, cells, int(c), hood)
        want = brute_force_neighbors_of(m, t, cells, int(c), hood)
        assert sorted(got) == sorted(want), f"cell {c}"


def test_finer_neighbors_expand_to_8_in_z_order():
    m, t, cells = refined_grid((2, 1, 1), 1, [2])
    hood = make_neighborhood(0)
    got = engine_neighbors_of(m, t, cells, 1, hood)
    # +x face of cell 1 is refined cell 2 -> all 8 children in z-order
    plus_x = [(v, o) for it, v, o in got if it == 3]
    assert len(plus_x) == 8
    kids = m.get_all_children(np.uint64(2))
    np.testing.assert_array_equal([v for v, _ in plus_x], kids)
    # offsets: window at +2 (cell edge 2 in smallest units), children at
    # relative 0/1 in each dim, z-order x fastest
    assert [o for _, o in plus_x] == [
        (2, 0, 0), (3, 0, 0), (2, 1, 0), (3, 1, 0),
        (2, 0, 1), (3, 0, 1), (2, 1, 1), (3, 1, 1),
    ]


def test_coarser_neighbor_offset():
    m, t, cells = refined_grid((2, 1, 1), 1, [1])
    # children of cell 1; the +x-most children see coarse cell 2
    kids = m.get_all_children(np.uint64(1))
    hood = make_neighborhood(0)
    # child 1 at indices (1,0,0), +x window at (2,0,0): coarse cell 2
    got = engine_neighbors_of(m, t, cells, int(kids[1]), hood)
    plus_x = [(v, o) for it, v, o in got if it == 3]
    assert plus_x == [(2, (1, 0, 0))]
    # child 3 at (1,1,0): +x window (2,1,0), coarse min (2,0,0) -> rel y -1
    got = engine_neighbors_of(m, t, cells, int(kids[3]), hood)
    plus_x = [(v, o) for it, v, o in got if it == 3]
    assert plus_x == [(2, (1, -1, 0))]


def test_random_refined_grids_match_brute_force(rng):
    for trial in range(3):
        length = tuple(rng.integers(1, 4, size=3))
        n0 = int(np.prod(length))
        picks = rng.choice(np.arange(1, n0 + 1), size=min(2, n0), replace=False)
        m, t, cells = refined_grid(length, 2, picks, periodic=(True, trial % 2 == 0, False))
        hood = make_neighborhood(1)
        sample = rng.choice(cells, size=min(12, len(cells)), replace=False)
        for c in sample:
            got = engine_neighbors_of(m, t, cells, int(c), hood)
            want = brute_force_neighbors_of(m, t, cells, int(c), hood)
            assert sorted(got) == sorted(want), f"len {length} picks {picks} cell {c}"


def test_neighbors_to_inversion():
    m, t, cells = refined_grid((2, 2, 1), 1, [3])
    nl = build_neighbor_lists(m, t, cells, make_neighborhood(1))
    # to-relation is the exact inverse of the of-relation
    of_pairs = set(zip(cells[nl.of_source].tolist(), nl.of_neighbor.tolist()))
    to_pairs = set(zip(nl.to_neighbor.tolist(), cells[nl.to_source].tolist()))
    assert of_pairs == to_pairs
    # offsets negate
    of_map = {}
    for s, v, o in zip(cells[nl.of_source], nl.of_neighbor, nl.of_offset):
        of_map.setdefault((int(s), int(v)), set()).add(tuple(o))
    for v_row, c, o in zip(nl.to_source, nl.to_neighbor, nl.to_offset):
        v = int(cells[v_row])
        assert tuple(-np.asarray(o)) in of_map[(int(c), v)]


def test_verify_tiling_catches_errors():
    m = Mapping((2, 2, 2), maximum_refinement_level=1)
    cells = np.arange(1, 9, dtype=np.uint64)
    verify_tiling(m, cells)
    with pytest.raises(StructureError):
        verify_tiling(m, cells[:-1])  # gap
    kids = m.get_all_children(np.uint64(1))
    with pytest.raises(StructureError):
        verify_tiling(m, np.sort(np.concatenate([cells, kids])))  # overlap


def test_structure_error_on_gap():
    m = Mapping((2, 1, 1))
    t = GridTopology()
    cells = np.array([1], dtype=np.uint64)  # cell 2 missing
    with pytest.raises(StructureError):
        find_neighbors_of(m, t, cells, cells, make_neighborhood(0))
