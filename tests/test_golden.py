"""Golden-file checkpoint format pin (VERDICT r3 item 7).

tests/data/golden.dc is a canned checkpoint with known contents.
Loading it must reconstruct the exact structure and data; re-saving
must reproduce the file byte for byte — any .dc layout change fails
here before it can orphan existing checkpoints."""

import os

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dccrg_tpu.grid import Grid
from golden_fixture import GOLDEN_SCHEMA, GOLDEN_VARIABLE, build_golden_grid

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "golden.dc")
HEADER = b"golden-v1\n"


def _load(mesh):
    return Grid.from_file(GOLDEN, cell_data=GOLDEN_SCHEMA, mesh=mesh,
                          header_size=len(HEADER),
                          variable=GOLDEN_VARIABLE)


@pytest.mark.parametrize("ndev", [1, 8])
def test_golden_file_contents(ndev):
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("dev",))
    g, _ = _load(mesh)
    cells = np.asarray(g.plan.cells)
    assert len(cells) == 46  # 32 level-0 - 2 refined + 16 children
    assert np.uint64(1) not in cells and np.uint64(22) not in cells
    # known per-cell values (partition-independent, derived from ids)
    np.testing.assert_allclose(
        g.get("density", cells), cells.astype(np.float64) * 0.5, rtol=1e-7)
    np.testing.assert_array_equal(
        g.get("flag", cells), (cells % np.uint64(7)).astype(np.int32))
    counts = g.get("count", cells)
    np.testing.assert_array_equal(counts, (cells % np.uint64(5)).astype(np.int32))
    pos = g.get("pos", cells)
    ids = cells.astype(np.float64)
    for r in range(4):
        for c in range(3):
            m = counts > r  # only rows < count are stored/restored
            np.testing.assert_allclose(
                pos[m, r, c], (ids[m] * (r + 1) + c).astype(np.float32),
                rtol=1e-7)


@pytest.mark.parametrize("ndev", [1, 8])
def test_golden_file_roundtrip_bytes(tmp_path, ndev):
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("dev",))
    g, header = _load(mesh)
    assert header == HEADER
    out = tmp_path / "resave.dc"
    g.save_grid_data(str(out), header=HEADER, variable=GOLDEN_VARIABLE)
    assert out.read_bytes() == open(GOLDEN, "rb").read()


def test_golden_matches_fresh_build():
    """The fixture is reproducible from the deterministic builder."""
    g = build_golden_grid(Mesh(np.array(jax.devices()[:4]), ("dev",)))
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".dc") as f:
        g.save_grid_data(f.name, header=HEADER, variable=GOLDEN_VARIABLE)
        assert open(f.name, "rb").read() == open(GOLDEN, "rb").read()


def test_reference_write_sequence_loads(tmp_path):
    """Cross-compat statement for the .dc format: a file assembled by
    replaying the REFERENCE's write sequence with plain struct.pack —
    independent of this repo's serializers — must load via
    Grid.from_file. Write calls mirrored instruction by instruction:
    header, endianness u64 (dccrg.hpp:1240-1248), mapping record
    (dccrg_mapping.hpp:615-655: 3 x u64 length + i32 max_ref_lvl),
    neighborhood length u32 (dccrg.hpp:1281-1297), topology 3 x u8
    (dccrg_topology write), geometry id i32 + 3 x f64 start + 3 x f64
    cell length (dccrg_cartesian_geometry.hpp:620-672), cell count
    u64, (id, offset) u64 pairs, payloads (dccrg.hpp:1325-1420)."""
    import struct
    import jax.numpy as jnp

    header = b"ref-conformance\n"
    nx, ny, nz = 4, 2, 2
    max_ref = 1
    hood_len = 1
    start = (0.5, 0.0, -1.0)
    cell_len = (0.25, 0.5, 0.5)
    cells = np.arange(1, nx * ny * nz + 1, dtype=np.uint64)
    # payload per cell: one f32 field "rho" = 3 * id
    payload = (3.0 * cells).astype(np.float32)

    buf = bytearray()
    buf += header
    buf += struct.pack("<Q", 0x1234567890ABCDEF)
    buf += struct.pack("<3Qi", nx, ny, nz, max_ref)
    buf += struct.pack("<I", hood_len)
    buf += struct.pack("<3B", 1, 0, 0)  # periodic in x only
    buf += struct.pack("<i", 1)  # Cartesian_Geometry::geometry_id
    buf += struct.pack("<3d", *start)
    buf += struct.pack("<3d", *cell_len)
    buf += struct.pack("<Q", len(cells))
    data_start = len(buf) + 16 * len(cells)
    for i, c in enumerate(cells):
        buf += struct.pack("<QQ", int(c), data_start + 4 * i)
    buf += payload.tobytes()

    path = str(tmp_path / "ref_conformance.dc")
    with open(path, "wb") as f:
        f.write(bytes(buf))

    g, hdr = Grid.from_file(path, cell_data={"rho": jnp.float32},
                            header_size=len(header))
    assert hdr == header
    assert g.mapping.length.get().tolist() == [nx, ny, nz]
    assert g.mapping.max_refinement_level == max_ref
    assert g._hood_len == hood_len
    assert [g.topology.is_periodic(d) for d in range(3)] == [True, False, False]
    assert g.geometry.geometry_id == 1
    np.testing.assert_allclose(g.geometry.start, start)
    np.testing.assert_allclose(g.geometry.level_0_cell_length, cell_len)
    np.testing.assert_allclose(
        g.get("rho", np.asarray(g.plan.cells)), 3.0 * cells)
    # and the round trip back out is byte-identical to the
    # reference-sequence bytes
    out2 = tmp_path / "ref_conformance2.dc"
    g.save_grid_data(str(out2), header=header)
    assert out2.read_bytes() == bytes(buf)


def test_legacy_length_prefixed_files_still_load(tmp_path):
    """Pre-round-4 files carried a u32 geometry-record-length prefix
    (and stretched records without coordinate counts); they must keep
    loading through the legacy fallback."""
    import struct
    import jax.numpy as jnp

    nx, ny, nz = 2, 2, 1
    cells = np.arange(1, 5, dtype=np.uint64)
    payload = (1.5 * cells).astype(np.float32)

    def base(geom_record):
        buf = bytearray()
        buf += struct.pack("<Q", 0x1234567890ABCDEF)
        buf += struct.pack("<3Qi", nx, ny, nz, 0)
        buf += struct.pack("<I", 1)
        buf += struct.pack("<3B", 0, 0, 0)
        buf += struct.pack("<I", len(geom_record)) + geom_record  # legacy
        buf += struct.pack("<Q", len(cells))
        ds = len(buf) + 16 * len(cells)
        for i, c in enumerate(cells):
            buf += struct.pack("<QQ", int(c), ds + 4 * i)
        buf += payload.tobytes()
        return bytes(buf)

    # legacy cartesian (id + 6 doubles, no counts involved)
    cart = struct.pack("<i", 1) + struct.pack("<6d", 0, 0, 0, .5, .5, 1)
    p = tmp_path / "legacy_cart.dc"
    p.write_bytes(base(cart))
    g, _ = Grid.from_file(str(p), cell_data={"rho": jnp.float32})
    assert g.geometry.geometry_id == 1
    np.testing.assert_allclose(g.get("rho", np.asarray(g.plan.cells)),
                               1.5 * cells)
    # legacy stretched (id + raw coordinate arrays, NO counts)
    coords = [np.array([0., 1., 2.]), np.array([0., .5, 1.]),
              np.array([0., 2.])]
    stretched = struct.pack("<i", 2) + b"".join(
        c.astype(np.float64).tobytes() for c in coords)
    p2 = tmp_path / "legacy_stretched.dc"
    p2.write_bytes(base(stretched))
    g2, _ = Grid.from_file(str(p2), cell_data={"rho": jnp.float32})
    assert g2.geometry.geometry_id == 2
    np.testing.assert_allclose(g2.geometry.coordinates[1], coords[1])
