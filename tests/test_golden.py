"""Golden-file checkpoint format pin (VERDICT r3 item 7).

tests/data/golden.dc is a canned checkpoint with known contents.
Loading it must reconstruct the exact structure and data; re-saving
must reproduce the file byte for byte — any .dc layout change fails
here before it can orphan existing checkpoints."""

import os

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dccrg_tpu.grid import Grid
from golden_fixture import GOLDEN_SCHEMA, GOLDEN_VARIABLE, build_golden_grid

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "golden.dc")
HEADER = b"golden-v1\n"


def _load(mesh):
    return Grid.from_file(GOLDEN, cell_data=GOLDEN_SCHEMA, mesh=mesh,
                          header_size=len(HEADER),
                          variable=GOLDEN_VARIABLE)


@pytest.mark.parametrize("ndev", [1, 8])
def test_golden_file_contents(ndev):
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("dev",))
    g, _ = _load(mesh)
    cells = np.asarray(g.plan.cells)
    assert len(cells) == 46  # 32 level-0 - 2 refined + 16 children
    assert np.uint64(1) not in cells and np.uint64(22) not in cells
    # known per-cell values (partition-independent, derived from ids)
    np.testing.assert_allclose(
        g.get("density", cells), cells.astype(np.float64) * 0.5, rtol=1e-7)
    np.testing.assert_array_equal(
        g.get("flag", cells), (cells % np.uint64(7)).astype(np.int32))
    counts = g.get("count", cells)
    np.testing.assert_array_equal(counts, (cells % np.uint64(5)).astype(np.int32))
    pos = g.get("pos", cells)
    ids = cells.astype(np.float64)
    for r in range(4):
        for c in range(3):
            m = counts > r  # only rows < count are stored/restored
            np.testing.assert_allclose(
                pos[m, r, c], (ids[m] * (r + 1) + c).astype(np.float32),
                rtol=1e-7)


@pytest.mark.parametrize("ndev", [1, 8])
def test_golden_file_roundtrip_bytes(tmp_path, ndev):
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("dev",))
    g, header = _load(mesh)
    assert header == HEADER
    out = tmp_path / "resave.dc"
    g.save_grid_data(str(out), header=HEADER, variable=GOLDEN_VARIABLE)
    assert out.read_bytes() == open(GOLDEN, "rb").read()


def test_golden_matches_fresh_build():
    """The fixture is reproducible from the deterministic builder."""
    g = build_golden_grid(Mesh(np.array(jax.devices()[:4]), ("dev",)))
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".dc") as f:
        g.save_grid_data(f.name, header=HEADER, variable=GOLDEN_VARIABLE)
        assert open(f.name, "rb").read() == open(GOLDEN, "rb").read()
