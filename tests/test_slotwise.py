"""SlotwiseKernel (slot-at-a-time stencils) must match the dense
kernel contract.

The slot-wise protocol exists so the bulk pass never materializes the
[L, S] neighbor stack / [L, S, 3] offsets — at 512^3 those are
multi-GB HBM temps that OOM a single chip (the round-5 chip session's
finding).  Equivalence is checked with integer-valued float32 fields:
every sum is exact, so slot-order reassociation cannot hide a wrong
gather, mask, or offset.

Reference behavior being reproduced: dccrg's solver loop reads each
neighbor's data through the cached neighbor lists one neighbor at a
time (dccrg.hpp:5046-5413) — slot-wise is the same access pattern,
table-driven, inside one XLA program.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID, Grid, SlotwiseKernel


def _mk(monkeypatch, *, roll, refine=False, overlap=False,
        length=(8, 8, 40), periodic=(True, True, False)):
    monkeypatch.setenv("DCCRG_ROLL_STENCIL", "1" if roll else "0")
    monkeypatch.setenv("DCCRG_OVERLAP", "1" if overlap else "0")
    g = (
        Grid(cell_data={"v": jnp.float32, "w": jnp.float32})
        .set_initial_length(length)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(2 if refine else 0)
        .set_neighborhood_length(1)
        .initialize(partition="block")
    )
    if refine:
        for cid in g.local_cells().ids[:6:2]:
            g.refine_completely(int(cid))
        g.stop_refining()
    cells = g.plan.cells
    rng = np.random.default_rng(11)
    g.set("v", cells, rng.integers(0, 64, len(cells)).astype(np.float32))
    g.set("w", cells, rng.integers(0, 64, len(cells)).astype(np.float32))
    g.update_copies_of_remote_neighbors()
    return g


def _dense_kern(cell, nbr, offs, mask, *extra):
    # weights depend on the offset so a mixed-up slot <-> offset
    # pairing changes the result
    wgt = jnp.where(mask & (offs[..., 0] == 1), 2.0,
                    jnp.where(mask, 1.0, 0.0))
    s = jnp.sum(wgt * jnp.where(mask, nbr["v"], 0.0), axis=1)
    return {"v": cell["v"] + s + jnp.sum(
        jnp.where(mask, nbr["w"], 0.0), axis=1)}


def _slot_kern():
    def init(cell, *extra):
        return jnp.zeros(cell["v"].shape, jnp.float32)

    def slot(acc, cell, nbr, offs, mask, *extra):
        wgt = jnp.where(mask & (offs[..., 0] == 1), 2.0,
                        jnp.where(mask, 1.0, 0.0))
        return acc + wgt * jnp.where(mask, nbr["v"], 0.0) + jnp.where(
            mask, nbr["w"], 0.0)

    def finish(acc, cell, *extra):
        return {"v": cell["v"] + acc}

    return SlotwiseKernel(init, slot, finish)


@pytest.mark.parametrize("roll", [False, True])
@pytest.mark.parametrize("refine", [False, True])
def test_apply_stencil_matches_dense(monkeypatch, roll, refine):
    """Slot-wise apply_stencil == dense apply_stencil, bitwise (integer
    fields), on both gather modes and with the AMR split (hard-rows)
    pass."""
    g = _mk(monkeypatch, roll=roll, refine=refine)
    cells = g.plan.cells
    v0 = g.get("v", cells).copy()
    g.apply_stencil(_dense_kern, ["v", "w"], ["v"])
    want = g.get("v", cells).copy()

    g.set("v", cells, v0)
    g.update_copies_of_remote_neighbors()
    g.apply_stencil(_slot_kern(), ["v", "w"], ["v"])
    np.testing.assert_array_equal(g.get("v", cells), want)


@pytest.mark.parametrize("roll", [False, True])
@pytest.mark.parametrize("overlap", [False, True])
def test_run_steps_matches_dense(monkeypatch, roll, overlap):
    """Slot-wise fused step loop == dense fused step loop, bitwise,
    with and without the overlapped (inner/outer) execution."""
    g = _mk(monkeypatch, roll=roll, overlap=overlap)
    cells = g.plan.cells
    v0 = g.get("v", cells).copy()
    g.run_steps(_dense_kern, ["v", "w"], ["v"], 2)
    want = g.get("v", cells).copy()
    assert np.all(np.isfinite(want)) and want.max() < 2 ** 24

    g.set("v", cells, v0)
    g.update_copies_of_remote_neighbors()
    g.run_steps(_slot_kern(), ["v", "w"], ["v"], 2)
    np.testing.assert_array_equal(g.get("v", cells), want)


def test_advection_kernel_is_slotwise_and_matches_dense_math():
    """The headline GridAdvection kernel ships as a SlotwiseKernel and
    its dense __call__ adapter reproduces the pre-slotwise dense
    upwind-flux arithmetic exactly."""
    from dccrg_tpu.models.advection import make_uniform_flux_kernel

    kern = make_uniform_flux_kernel((0.25, 0.25, 1.0))
    assert isinstance(kern, SlotwiseKernel)

    rng = np.random.default_rng(3)
    L, S = 64, 6
    cell = {n: jnp.asarray(rng.random(L, dtype=np.float32))
            for n in ("density", "vx", "vy")}
    nbr = {n: jnp.asarray(rng.random((L, S), dtype=np.float32))
           for n in ("density", "vx", "vy")}
    offs = np.zeros((L, S, 3), np.int32)
    offs[:, 0, 0], offs[:, 1, 0] = 1, -1
    offs[:, 2, 1], offs[:, 3, 1] = 1, -1
    offs[:, 4, 2], offs[:, 5, 2] = 1, -1
    mask = np.ones((L, S), bool)
    mask[:, 5] = False
    dt = jnp.float32(0.01)

    got = kern(cell, nbr, jnp.asarray(offs), jnp.asarray(mask), dt)

    # the pre-slotwise dense reference (same math, [L, S] layout)
    f32 = jnp.float32
    inv = [4.0, 4.0, 1.0]
    rho_c = cell["density"][:, None]
    rho_n = nbr["density"]
    acc = jnp.zeros_like(rho_n)
    m_ = jnp.asarray(mask)
    o_ = jnp.asarray(offs)
    for d, vname in ((0, "vx"), (1, "vy")):
        v = 0.5 * (cell[vname][:, None] + nbr[vname])
        up_pos = jnp.where(v >= 0, rho_c, rho_n)
        up_neg = jnp.where(v >= 0, rho_n, rho_c)
        face_pos = m_ & (o_[..., d] == 1)
        face_neg = m_ & (o_[..., d] == -1)
        mm = v * (dt * f32(inv[d]))
        acc = acc - jnp.where(face_pos, up_pos * mm, 0.0)
        acc = acc + jnp.where(face_neg, up_neg * mm, 0.0)
    want = cell["density"] + jnp.sum(acc, axis=1)
    np.testing.assert_allclose(np.asarray(got["density"]),
                               np.asarray(want), rtol=2e-6, atol=2e-7)


def test_grid_advection_physics_on_slotwise_path():
    """End-to-end: the (now slot-wise) GridAdvection still advects —
    mass is conserved and the hump moves (l2 error stays finite and
    small) on a small periodic grid."""
    from dccrg_tpu.models.advection import GridAdvection

    adv = GridAdvection(n=24, nz=1)
    rho0 = adv.density().sum()
    for _ in range(8):
        adv.run(4)
    rho1 = adv.density().sum()
    np.testing.assert_allclose(rho0, rho1, rtol=1e-4)
    assert adv.l2_error() < 0.2


def test_single_device_closed_form_roll3d_matches_dense(monkeypatch):
    """On a single-device closed-form plan the slot gather lowers to
    exact 3-D rolls (no fixup scatter); results must stay bitwise equal
    to the dense kernel across periodic and walled dimensions."""
    import jax

    monkeypatch.setenv("DCCRG_ROLL_STENCIL", "1")
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("dev",))
    g = (
        Grid(cell_data={"v": jnp.float32, "w": jnp.float32})
        .set_initial_length((6, 5, 4))
        .set_periodic(True, False, True)
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .initialize(mesh, partition="block")
    )
    hood = g.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    assert hood.closed_form is not None and not hood.closed_form.get(
        "multi")
    cells = g.plan.cells
    rng = np.random.default_rng(5)
    g.set("v", cells, rng.integers(0, 64, len(cells)).astype(np.float32))
    g.set("w", cells, rng.integers(0, 64, len(cells)).astype(np.float32))
    v0 = g.get("v", cells).copy()
    g.apply_stencil(_dense_kern, ["v", "w"], ["v"])
    want = g.get("v", cells).copy()
    g.run_steps(_dense_kern, ["v", "w"], ["v"], 2)
    want2 = g.get("v", cells).copy()

    g.set("v", cells, v0)
    g.apply_stencil(_slot_kern(), ["v", "w"], ["v"])
    np.testing.assert_array_equal(g.get("v", cells), want)
    g.run_steps(_slot_kern(), ["v", "w"], ["v"], 2)
    np.testing.assert_array_equal(g.get("v", cells), want2)


def test_slotwise_include_to_raises(monkeypatch):
    g = _mk(monkeypatch, roll=False)
    with pytest.raises(ValueError, match="include_to"):
        g.apply_stencil(_slot_kern(), ["v", "w"], ["v"], include_to=True)
