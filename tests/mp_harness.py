#!/usr/bin/env python
"""REAL multi-host smoke harness: N actual OS processes under
``jax.distributed.initialize`` on the CPU backend.

Everything multi-process in this repo was historically validated by
FAKED process splits (tests/test_multiprocess.py) — the ROADMAP's
"Real multi-host smoke" open item. This harness closes it: a parent
process spawns N children, each a real ``jax.distributed`` rank with
its own 2 virtual CPU devices (gloo cross-process collectives), and
drives the scenarios the faked splits cannot truthfully exercise:

- ``save_restore``  — the two-phase-commit checkpoint save with REAL
  barriers and the REAL cross-rank CRC all-gather, then a per-rank
  slice load, verified against the expected values on every rank.
- ``psum``          — ``checkpoint._replicated_pull`` consistency: the
  psum device gather must return bit-identical values on every rank
  (the property the offset table of every multi-process save depends
  on).
- ``barrier_timeout`` — a rank that never reaches the barrier: its
  peer must get a typed BarrierTimeoutError within the configured
  bound, not a hang.
- ``rank_kill``     — a FaultPlan ``rank_death`` fires mid-slice on
  rank 1, which really exits the OS process; rank 0's commit barrier
  times out, the PREVIOUS checkpoint is verified bitwise intact and
  still loads.
- ``consensus``     — ResilientRunner's distributed trip consensus: a
  MutationAbortedError raised on ONE rank makes every rank roll back
  to the same checkpoint and the final states agree bit-for-bit.
- ``sdc_rank``      — silent-data-corruption consensus: a FINITE
  bit-flip lands in ONE real rank's shard (invisible to the numerics
  watchdog and CRCs); the integrity layer's conservation invariant
  convicts it as a CORRUPT trip on EVERY rank, all ranks roll back
  together, and the recovered run reconverges bitwise with an
  uncorrupted reference.
- ``preempt``       — the SIGTERM round trip, in three phases: (ref)
  an uninterrupted supervised run records its final-state digest;
  (kill) the parent delivers a REAL ``kill -TERM`` to rank 1 mid-run
  — the trip consensus makes EVERY rank observe the preemption, take
  the collective two-phase emergency checkpoint (shortened barrier
  timeouts) and exit with the resumable code 75; (resume)
  ``supervise.resume_latest`` picks the emergency checkpoint up, the
  run completes, and its digest must equal ref's bit-for-bit.
- ``trace_merge``   — telemetry tracing across 2 real ranks: each
  rank records spans (steps, halo exchanges, the collective two-phase
  checkpoint save) with ``DCCRG_TRACE`` semantics, flushes its own
  JSONL trace file, and the rank-tagged files merge into ONE coherent
  wall-clock-ordered timeline (``telemetry.merge_traces``) whose
  collective-save spans overlap across ranks.
- ``delta_rank_kill`` — incremental (delta) checkpoints through the
  REAL two-phase commit, in two parts: (restore) a step loop writes a
  keyframe + dirty-field delta chain through real barriers and the
  real CRC all-gather, ``resume_latest`` replays the chain and the
  resumed run's digest must equal the uninterrupted run's
  bit-for-bit; (kill) a FaultPlan ``rank_death`` really exits one
  rank's OS process at EACH delta-commit phase (meta/slice/written on
  a slice writer, commit/publish on the committing rank — re-pointed
  at the non-leader, see DELTA_KILL_PHASES) — the survivor must get
  a typed timeout, the previous keyframe+delta chain must stay
  bitwise intact, and ``resume_latest`` must resume from it.
- ``host_death``    — the elastic multi-host fleet under a REAL
  ``kill -9`` of a worker rank mid-serve: every rank runs a
  rank-aware ``FleetScheduler`` (membership heartbeats + job leases
  in the REAL coordination KV store) over one shared checkpoint
  directory; the parent SIGKILLs rank 1 once it reports serving
  progress. The survivors detect the death within the lease bound,
  RECLAIM its jobs (CAS claim keys — exactly one winner each) and
  re-admit them from their checkpoint stems; EVERY job's final
  digest — the victims included — must be bitwise identical to an
  uninterrupted solo reference run.
- ``zombie_fence``  — the stale-owner fence: the parent SIGSTOPs
  rank 1 mid-serve until its leases expire and a survivor reclaims
  its jobs, then SIGCONTs it. The resumed zombie's renew must raise
  a typed ``OwnershipLostError`` and drop the jobs locally WITHOUT
  publishing (the reclaimer's chain verifies intact via
  ``verify_chain``); every job still drains bitwise-solo.
- ``host_rejoin``   — elastic regrow: after the zombie round trip, a
  second wave of jobs enters every rank's queue once rank 1 is
  observed live again, and the deterministic partition hands the
  rejoined rank work it serves to completion.
- ``amr_commit``    — distributed AMR (dccrg_tpu/distamr.py): the
  ranks run two adapt epochs end to end — rank-local refines, the
  sealed proposal exchange, resolve/prepare digest agreement, the
  epoch-fenced collective install — over the LIVE coordination KV.
  Plan digests must agree on every rank, the fence must advance
  exactly once per epoch, and epoch 2 runs the background
  (PlanBuildWorker) prepare build. Prints per-epoch commit wall
  times (``AMR_COMMIT_SECONDS`` — the PERF.md numbers).
- ``amr_rank_kill`` — a FaultPlan ``rank_death`` really exits rank
  1's OS process at EACH commit phase in AMR_KILL_PHASES; the
  survivor must abort TYPED within its barrier bound and keep
  serving the OLD plan bitwise: structure digest, payload bytes and
  the restored (collectively retryable) request sets. See
  AMR_KILL_PHASES on why "prepare" is exercised by the faked tier-1
  suite and the fuzzer instead.
- ``amr_zombie``    — the stale proposer fence: rank 1 stalls inside
  the propose phase (an injected hang, plus a REAL SIGSTOP from the
  parent) past rank 0's barrier deadline; rank 0 aborts typed,
  stays on the old plan bitwise, and advances the fence — standing
  in for a re-formed fleet's commit. The woken zombie must LOSE:
  StaleFenceError, bitwise rollback, never a stale install.
- ``async_save``    — the async (writer-thread) two-phase mp save:
  each rank freezes through ``background.freeze_grid_mp`` and hands
  the save to an AsyncSaver writer — the REAL prepare/commit
  barriers rendezvous on the writer threads, the commit CRC table
  crosses through sealed KV records — while the main threads keep
  dispatching real collectives and mutate the LIVE grid. The
  published file must be byte-identical to a synchronous save of
  the same (pre-mutation) state.
- ``async_save_kill`` — a rank death on rank 1's WRITER thread
  mid-slice: the drain surfaces it on the main thread and the OS
  process really exits; rank 0's writer aborts typed at its barrier
  bound, the previous checkpoint stays bitwise intact, and nothing
  is ever published.
- ``intake_kill``    — the streaming-intake exactly-once admission
  proof (dccrg_tpu/intake.py): rank 0 drops job records into the
  shared spool; rank 1 claims one through the intake CAS lease,
  writes its journal record, and REALLY exits between the claim and
  the scheduler add (FaultPlan ``intake_death`` at site
  ``intake.claim``). Rank 0 must reclaim the orphaned admission
  within the lease bound, re-admit from the journal record, and
  drain EVERY job exactly once with bitwise-solo digests — no job
  lost, none run twice.
- ``rejoin_warm``    — the warm-start rejoin proof
  (dccrg_tpu/warmstart.py): three single-process phases over ONE
  shared ``DCCRG_COMPILE_CACHE`` dir (a SIGKILLed jax.distributed
  member cannot re-enter its old cluster — the coordination service
  reaps the survivors — so the rejoin is modeled the way it happens
  in production: the same host restarting as a fresh process over
  the same persistent cache). (cold) an empty cache: every first
  dispatch pays the trace+compile, the manifest records land.
  (serve) a warm restart that then upserts manifest records in a
  tight loop until the parent's REAL ``kill -9`` lands mid-write.
  (warm) the rejoin: the manifest must load with ONLY complete
  records (per-entry atomic rename — no torn record is ever
  visible), the pool pre-compiles every bucket before serving,
  every first dispatch is a warm hit ≥10× faster than the cold
  baseline, digests match the cold phase bitwise, and the intake
  gate never flaps across the churn window.

Runs are DETERMINISTIC: ``--seed`` drives the field values and fault
placement the same way fuzz.py's seeds do — two runs with the same
seed exercise byte-identical data.

Exit codes: 0 = all scenarios passed, 77 = environment cannot run
``jax.distributed`` on CPU (CI must treat as SKIP), 1 = failure.

Usage::

    python tests/mp_harness.py                     # all scenarios
    python tests/mp_harness.py --scenario rank_kill --seed 3
    python tests/mp_harness.py --procs 2 --timeout 240

What this harness still cannot cover: ICI-mesh collectives (the
sharded ppermute halo exchange on a real TPU torus) need a chip — the
gloo CPU backend validates the protocol, not the interconnect.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

SKIP_RC = 77
DEATH_RC = 17
RESUMABLE_RC = 75  # supervise.RESUMABLE_EXIT (EX_TEMPFAIL)
SCENARIOS = ("save_restore", "psum", "barrier_timeout", "rank_kill",
             "consensus", "sdc_rank", "preempt", "delta_rank_kill",
             "trace_merge", "host_death", "zombie_fence",
             "host_rejoin", "amr_commit", "amr_rank_kill",
             "amr_zombie", "async_save", "async_save_kill",
             "intake_kill", "rejoin_warm")
# elastic-fleet scenario knobs: tight heartbeat/lease bounds so the
# whole detect->reclaim->drain recovery fits inside the ~10 s window
# jax's coordination service grants survivors after a peer dies
FLEET_HEARTBEAT_S = 0.25
FLEET_LEASE_S = 1.0
# child-side phase names of the parent-orchestrated preempt scenario
PREEMPT_PHASES = ("preempt_ref", "preempt_kill", "preempt_resume")
PREEMPT_STEPS = 8
# child-side legs of the parent-orchestrated delta_rank_kill scenario
DELTA_LEGS = ("delta_restore", "delta_kill")
# two-phase-commit phases a rank death is injected at (checkpoint.mp
# fault sites). The death always lands on rank 1: rank 0 is the
# jax.distributed LEADER, and killing it takes the coordination
# service down with it — the service then hard-kills the survivor
# before it can recover, testing the service's liveness instead of
# our protocol. For commit/publish the committer role is re-pointed
# at rank 1 (the _ckpt_commits override checkpoint.py honors), so the
# death still lands on the committing rank mid-commit.
DELTA_KILL_PHASES = ("meta", "slice", "written", "commit", "publish")
# distributed-AMR commit phases a rank death is injected at (the
# faults.py dist-AMR sites; see faults.DIST_AMR_FAULT_SITES).
# "prepare" is deliberately NOT in this list: the survivor's prepare
# work IS a cross-process device gather (a shard_map psum), so with
# its peer already dead it blocks inside the gloo collective — the
# bound hit would be the runtime's, not the commit protocol's.
# Prepare-phase aborts are pinned by the faked tier-1 suite
# (tests/test_distamr.py) and the fuzzer's --dist-amr leg, where
# every rank's collectives run in one process.
AMR_KILL_PHASES = ("propose", "resolve", "commit")
AMR_KILL_SITES = {"propose": ("amr.propose", None),
                  "resolve": ("amr.resolve", None),
                  "commit": ("amr.install", "commit")}
# scenarios where one rank REALLY dies mid-run: at 2 processes the
# survivor is alone afterwards, and its graceful jax.distributed
# teardown blocks on the shutdown barrier the corpse never joins
# (this jaxlib waits instead of hard-killing) until the parent's
# deadline kill — so once every assertion has passed and the success
# marker is on disk, the lone survivor exits HARD (see child_main).
# Kept 2-proc-only: with >2 procs another survivor may still need the
# leader-hosted coordination service for its own asserts.
PEER_DEATH_SCENARIOS = frozenset(
    {"rank_kill", "delta_kill", "amr_kill", "async_save_kill",
     "intake_kill"})


# =====================================================================
# child side: one real jax.distributed rank
# =====================================================================

def _child_setup(args):
    """Environment BEFORE jax imports, then guarded distributed init."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2")
    os.environ.setdefault("JAX_ENABLE_X64", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    try:  # cross-process CPU collectives
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    from dccrg_tpu import coord

    # the guarded init IS part of what the harness smokes: transient
    # coordinator races retry with backoff instead of dying
    coord.distributed_init(f"127.0.0.1:{args.port}", args.procs,
                           args.rank, retries=3, backoff=0.5)
    assert jax.process_count() == args.procs
    return jax


def _kv_client():
    from jax._src import distributed

    return distributed.global_state.client


def _kv_allgather(key, value: str, rank: int, nprocs: int,
                  timeout_ms: int = 60000) -> list:
    """Tiny host-side allgather over the coordination KV store — for
    cross-rank ASSERTIONS (hash comparisons), independent of the XLA
    collectives under test."""
    client = _kv_client()
    client.key_value_set(f"{key}:{rank}", value)
    return [client.blocking_key_value_get(f"{key}:{r}", timeout_ms)
            for r in range(nprocs)]


def _mk_grid(seed: int, static_extra: bool = False):
    import numpy as np

    import jax.numpy as jnp

    from dccrg_tpu.grid import Grid

    # ``static_extra`` adds a wide field the step loop never writes —
    # the production shape incremental (delta) checkpoints exist for:
    # the dirty set {v} is then a PROPER subset of the schema, so a
    # periodic save really lands as a .dcd (a one-field grid would
    # keyframe every time: a delta of everything is pure overhead)
    schema = {"v": jnp.float32}
    if static_extra:
        schema["aux"] = ((4,), jnp.float32)
    g = (Grid(cell_data=schema)
         .set_initial_length((8, 8, 4))
         .set_periodic(True, True, False)
         .set_maximum_refinement_level(0)
         .set_neighborhood_length(1)
         # the METHOD, not a one-off: rollback's load_cells
         # repartitions with it, so ownership stays stable across
         # checkpoint restores
         .set_load_balancing_method("block")
         .initialize())
    cells = g.plan.cells
    # replicated full-cover init: every rank computes the same values
    # (seed-deterministic), put_sharded serves each rank's shards
    vals = _expected(cells, seed)
    g.set("v", cells, vals)
    if static_extra:
        g.set("aux", cells,
              np.tile(vals[:, None], (1, 4)).astype(np.float32) + 1.0)
    g.update_copies_of_remote_neighbors()
    return g


def _expected(cells, seed: int):
    import numpy as np

    return ((cells.astype(np.float64) * (seed + 3) % 97)
            .astype(np.float32))


def scenario_probe(args):
    """Cheapest possible end-to-end check that this environment can do
    real multi-process CPU jax at all: a cross-process psum."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dccrg_tpu import comm, coord
    from dccrg_tpu.grid import default_mesh

    mesh = default_mesh()
    n = mesh.devices.size
    got = comm.host_all_reduce(mesh, np.arange(n, dtype=np.float32), "sum")
    assert float(got) == n * (n - 1) / 2, got
    coord.barrier("probe", timeout=30)


def scenario_save_restore(args):
    import numpy as np

    import jax.numpy as jnp

    from dccrg_tpu import coord, resilience

    g = _mk_grid(args.seed)
    cells = g.plan.cells
    assert g._multiproc, "harness grid must span processes"
    fn = os.path.join(args.tmp, "ckpt.dc")
    # the two-phase save: REAL prepare/commit/done barriers + the real
    # cross-rank CRC all-gather; process 0 commits
    resilience.save_checkpoint(g, fn)
    assert resilience.verify_checkpoint(fn) == []
    rec = resilience.read_sidecar(fn)
    assert rec["slices"], "per-rank slice table missing"

    # per-rank slice load into a fresh grid
    g2 = _mk_grid(args.seed)
    g2.set("v", cells, np.zeros(len(cells), np.float32))
    g2.load_grid_data(fn)
    local = g2._proc_local_dev[g2.plan.owner]
    mine = cells[local]
    got = np.asarray(g2.get("v", mine))
    np.testing.assert_array_equal(got, _expected(mine, args.seed))
    # cross-rank agreement on the file bytes they all see
    with open(fn, "rb") as f:
        import zlib

        h = f"{zlib.crc32(f.read()):08x}"
    hashes = _kv_allgather("save_restore_crc", h, args.rank, args.procs)
    assert len(set(hashes)) == 1, hashes
    # the parent relays DIGEST lines: the seed-determinism test
    # compares them across two same-seed runs (byte-identical files)
    print(f"[rank {args.rank}] DIGEST save_restore {h}", flush=True)
    coord.barrier("save_restore_done", timeout=60)


def scenario_psum(args):
    import numpy as np

    from dccrg_tpu import checkpoint as checkpoint_mod
    from dccrg_tpu import coord

    g = _mk_grid(args.seed)
    cells = g.plan.cells
    pulled = checkpoint_mod._replicated_pull(g, "v", cells)
    np.testing.assert_array_equal(pulled, _expected(cells, args.seed))
    h = pulled.tobytes()
    import zlib

    hashes = _kv_allgather("psum_crc", f"{zlib.crc32(h):08x}",
                           args.rank, args.procs)
    assert len(set(hashes)) == 1, f"psum result differs: {hashes}"
    coord.barrier("psum_done", timeout=60)


def scenario_barrier_timeout(args):
    from dccrg_tpu import coord, faults

    t0 = time.monotonic()
    if args.rank == 1:
        # this rank's sync is replaced by an injected hang — it NEVER
        # reaches the barrier, exactly a lost rank from rank 0's view
        plan = faults.FaultPlan(seed=args.seed)
        plan.barrier_hang(tag="lost-rank")
        with plan:
            try:
                coord.barrier("lost-rank", timeout=4)
                raise AssertionError("hung rank's barrier returned")
            except coord.BarrierTimeoutError:
                pass
    else:
        try:
            coord.barrier("lost-rank", timeout=4)
            raise AssertionError("barrier returned without its peer")
        except coord.BarrierTimeoutError as e:
            assert e.tag == "lost-rank"
    elapsed = time.monotonic() - t0
    assert elapsed < 30, f"timeout not honored ({elapsed:.1f}s)"


def scenario_rank_kill(args):
    import numpy as np

    from dccrg_tpu import coord, faults, resilience

    # tight bound: jax's coordination service hard-kills survivors
    # ~10s after a peer dies, so the whole recovery must finish first
    # (the success marker file covers the teardown race either way)
    os.environ["DCCRG_BARRIER_TIMEOUT"] = "3"
    g = _mk_grid(args.seed)
    cells = g.plan.cells
    fn = os.path.join(args.tmp, "kill.dc")
    resilience.save_checkpoint(g, fn)  # the good checkpoint
    assert resilience.verify_checkpoint(fn) == []
    with open(fn, "rb") as f:
        good = f.read()

    # new state that must never reach the final name
    g.set("v", cells, np.full(len(cells), 123.0, np.float32))
    if args.rank == 1:
        plan = faults.FaultPlan(seed=args.seed)
        plan.rank_death(phase="slice", rank=None)
        with plan:
            resilience.save_checkpoint(g, fn)  # raises InjectedRankDeath
        raise AssertionError("rank 1 should have died mid-slice")
    # rank 0: the peer dies mid-slice; the commit barrier must time
    # out instead of hanging, and the old checkpoint must survive
    try:
        resilience.save_checkpoint(g, fn)
        raise AssertionError("save completed despite a dead rank")
    except coord.BarrierTimeoutError as e:
        assert "save_commit" in e.tag or "save_prepare" in e.tag, e.tag
    with open(fn, "rb") as f:
        assert f.read() == good, "dead rank tore the old checkpoint"
    assert resilience.verify_checkpoint(fn) == []
    # the survivor can still restore from it (alone — its dead peer's
    # cells stay zero, exactly the salvage contract)
    g3 = _mk_grid(args.seed)
    g3.set("v", cells, np.zeros(len(cells), np.float32))
    g3.load_grid_data(fn)
    local = g3._proc_local_dev[g3.plan.owner]
    mine = cells[local]
    np.testing.assert_array_equal(np.asarray(g3.get("v", mine)),
                                  _expected(mine, args.seed))


def scenario_consensus(args):
    import zlib

    import numpy as np

    import jax.numpy as jnp

    from dccrg_tpu.resilience import ResilientRunner
    from dccrg_tpu.txn import MutationAbortedError

    cells = None

    def make_runner(name, inject: bool):
        nonlocal cells
        g = _mk_grid(args.seed)
        cells = g.plan.cells
        tripped = []

        def step_fn(grid, i):
            # the collective compute phase runs on EVERY rank first —
            # a one-sided host failure can only originate in host-
            # local work (I/O, host memory), which follows it
            grid.run_steps(
                lambda c, n, o, m: {"v": 0.5 * c["v"] + 0.125 * jnp.sum(
                    jnp.where(m, n["v"], 0.0), axis=1)},
                ["v"], ["v"], 1)
            if inject and args.rank == 1 and i == 3 and not tripped:
                # ...and fails on THIS rank only: a failed host-side
                # mutation, already rolled back by txn. Without the
                # per-step consensus rank 0 — which saw a clean step —
                # would advance and deadlock in the next collective.
                tripped.append(i)
                raise MutationAbortedError(
                    "injected adapt", RuntimeError("mp-harness"),
                    cells=[1])

        return ResilientRunner(
            g, step_fn, os.path.join(args.tmp, f"{name}.dc"),
            check_every=100, checkpoint_every=2, backoff=0.0,
            diagnostics_dir=args.tmp), g

    from dccrg_tpu import checkpoint as checkpoint_mod

    # reference: the undisturbed run (aligned on every rank)
    ref_runner, ref_g = make_runner("ref", inject=False)
    ref_runner.run(6)
    ref_bytes = checkpoint_mod._replicated_pull(
        ref_g, "v", cells).tobytes()

    runner, g = make_runner("cons", inject=True)
    runner.run(6)
    assert runner.step == 6
    # EVERY rank rolled back — including rank 0, which never saw the
    # error locally; that is the consensus working
    assert runner.rollbacks == 1, (
        f"rank {args.rank}: rollbacks={runner.rollbacks}")
    assert runner.trips, "no trip recorded"
    if args.rank != 1:
        assert runner.trips[0]["fields"].get("remote_rank_trip") == [], \
            runner.trips[0]["fields"]
    # and the recovered run reconverges bitwise with the reference
    got = checkpoint_mod._replicated_pull(g, "v", cells).tobytes()
    assert got == ref_bytes, "recovered state diverged from reference"
    hs = _kv_allgather(
        "consensus_state", f"{zlib.crc32(got):08x}", args.rank,
        args.procs)
    assert len(set(hs)) == 1, hs


def scenario_sdc_rank(args):
    """Silent-data-corruption consensus: a FINITE bit-flip lands in
    ONE real rank's shard mid-run — invisible to the numerics
    watchdog (everything stays finite) and to checkpoint CRCs. The
    integrity layer's conservation-sum invariant (a device-side
    collective, replicated result) must convict it as a CORRUPT trip
    on EVERY rank together, roll all ranks back to the same pre-flip
    checkpoint, and the recovered run must reconverge bitwise with an
    uncorrupted reference."""
    import zlib

    import numpy as np

    import jax.numpy as jnp

    from dccrg_tpu import checkpoint as checkpoint_mod
    from dccrg_tpu.faults import FaultPlan
    from dccrg_tpu.resilience import ResilientRunner

    def kern(c, n, o, m):
        # a genuinely conservative relaxation: the symmetric neighbor
        # redistribution keeps sum(v) exact in real arithmetic, which
        # is what gives the integrity invariant its teeth
        s = jnp.sum(jnp.where(m, n["v"], 0.0), axis=1)
        deg = jnp.sum(m, axis=1).astype(c["v"].dtype)
        return {"v": c["v"] + 0.02 * (s - deg * c["v"])}

    cells = None

    def make_runner(name):
        nonlocal cells
        g = _mk_grid(args.seed)
        cells = g.plan.cells

        def step_fn(grid, i):
            grid.run_steps(kern, ["v"], ["v"], 1)

        return ResilientRunner(
            g, step_fn, os.path.join(args.tmp, f"{name}.dc"),
            check_every=2, checkpoint_every=2, backoff=0.0,
            conserved_fields=("v",), diagnostics_dir=args.tmp), g

    # reference: the undisturbed run (aligned on every rank)
    ref_runner, ref_g = make_runner("sdc_ref")
    ref_runner.run(6)
    assert not ref_runner.trips, (
        f"rank {args.rank}: false SDC alarm {ref_runner.trips}")
    ref_bytes = checkpoint_mod._replicated_pull(
        ref_g, "v", cells).tobytes()

    runner, g = make_runner("sdc")
    plan = None
    if args.rank == 1:
        # the flip lands on rank 1 ONLY, in a locally-owned cell with
        # a non-trivial value (a near-zero cell would corrupt below
        # the conservation tolerance — plausible bits, tiny sum move)
        mine = cells[g._proc_local_dev[g.plan.owner]]
        vals = np.asarray(g.get("v", mine)).reshape(len(mine), -1)
        victim = mine[int(np.argmax(vals[:, 0]))]
        plan = FaultPlan(seed=args.seed)
        plan.silent_flip("v", step=3, cells=[int(victim)], bit=23)
        plan.__enter__()
    try:
        runner.run(6)
    finally:
        if plan is not None:
            plan.__exit__(None, None, None)
    if args.rank == 1:
        assert plan.fired("step.flip") == 1, plan.log
    assert runner.step == 6
    # EVERY rank took the CORRUPT verdict and rolled back — including
    # rank 0, whose local bytes never changed; that is the consensus
    # working on a fault only the integrity layer can see
    assert runner.rollbacks == 1, (
        f"rank {args.rank}: rollbacks={runner.rollbacks}")
    assert runner.trips, "no CORRUPT trip recorded"
    assert "v" in runner.trips[0]["fields"] \
        or "remote_rank_corrupt" in runner.trips[0]["fields"], \
        runner.trips[0]["fields"]
    got = checkpoint_mod._replicated_pull(g, "v", cells).tobytes()
    assert got == ref_bytes, "recovered state diverged from reference"
    hs = _kv_allgather(
        "sdc_state", f"{zlib.crc32(got):08x}", args.rank, args.procs)
    assert len(set(hs)) == 1, hs
    print(f"[rank {args.rank}] DIGEST sdc {hs[0]}", flush=True)


def _sup_kernel(c, nbr, offs, mask):
    import jax.numpy as jnp

    return {"v": 0.5 * c["v"] + 0.125 * jnp.sum(
        jnp.where(mask, nbr["v"], 0.0), axis=1)}


_DELTA_SCHEMA = None  # set lazily (jnp import must follow _child_setup)


def _delta_schema():
    global _DELTA_SCHEMA
    if _DELTA_SCHEMA is None:
        import jax.numpy as jnp

        _DELTA_SCHEMA = {"v": jnp.float32, "aux": ((4,), jnp.float32)}
    return _DELTA_SCHEMA


def scenario_delta_restore(args):
    """Incremental (delta) checkpoints through the REAL two-phase
    commit: a step loop writes a keyframe + dirty-field delta chain
    (real prepare/commit/done barriers, real cross-rank CRC
    all-gather), ``resume_latest`` replays the whole chain, and the
    resumed run's state must be bitwise identical to the live run
    that never stopped — the acceptance digest of the incremental
    data plane."""
    import zlib

    import numpy as np

    from dccrg_tpu import checkpoint as checkpoint_mod
    from dccrg_tpu import coord, resilience, supervise

    store_dir = os.path.join(args.tmp, "store")
    os.makedirs(store_dir, exist_ok=True)
    g = _mk_grid(args.seed, static_extra=True)
    cells = g.plan.cells
    store = supervise.CheckpointStore(store_dir, keyframe_every=8)

    paths = [store.save(g, 0)]
    for s in range(1, 5):
        g.run_steps(_sup_kernel, ["v"], ["v"], 1)
        paths.append(store.save(g, s))
    names = [os.path.basename(p) for p in paths]
    assert paths[0].endswith(".dc") and all(
        p.endswith(resilience.DELTA_SUFFIX) for p in paths[1:]), names
    rec = resilience.read_sidecar(paths[-1])
    assert rec["slices"], "two-phase delta must carry the slice table"
    assert resilience.verify_chain(paths[-1])

    # the uninterrupted reference IS the live grid; the resumed grid
    # must shadow it bitwise from here on
    info = supervise.resume_latest(store_dir, _delta_schema(),
                                   load_balancing_method="block")
    assert info is not None and info.step == 4 and not info.salvaged
    assert len(info.report.chain) == 5, info.report.chain
    g2 = info.grid
    g2.update_copies_of_remote_neighbors()
    for _ in range(2):
        g.run_steps(_sup_kernel, ["v"], ["v"], 1)
        g2.run_steps(_sup_kernel, ["v"], ["v"], 1)
    want = checkpoint_mod._replicated_pull(g, "v", cells).tobytes()
    got = checkpoint_mod._replicated_pull(g2, "v", cells).tobytes()
    assert got == want, \
        "resumed delta chain diverged from the uninterrupted run"
    h = f"{zlib.crc32(got):08x}"
    hashes = _kv_allgather("delta_restore_crc", h, args.rank, args.procs)
    assert len(set(hashes)) == 1, hashes
    print(f"[rank {args.rank}] DIGEST delta_restore {h}", flush=True)
    coord.barrier("delta_restore_done", timeout=60)


def scenario_delta_kill(args):
    """One REAL rank death at the two-phase delta-commit phase named
    by ``--phase``: the dying rank's OS process exits mid-protocol
    (InjectedRankDeath -> hard exit in child_main). The survivor must
    get a typed timeout within the configured bound — never a hang —
    the previous keyframe+delta chain must stay bitwise intact on
    disk, and ``resume_latest`` must restore the pre-kill step from
    it."""
    import numpy as np

    from dccrg_tpu import coord, faults, resilience, supervise

    assert args.phase in DELTA_KILL_PHASES, args.phase
    # tight bound, same reasoning as scenario_rank_kill: jax's
    # coordination service hard-kills survivors ~10s after a peer
    # dies, so the whole recovery must finish first
    os.environ["DCCRG_BARRIER_TIMEOUT"] = "3"
    store_dir = os.path.join(args.tmp, f"store_{args.phase}")
    os.makedirs(store_dir, exist_ok=True)
    g = _mk_grid(args.seed, static_extra=True)
    cells = g.plan.cells
    store = supervise.CheckpointStore(store_dir, keyframe_every=8)

    kf = store.save(g, 0)
    g.run_steps(_sup_kernel, ["v"], ["v"], 1)
    d1 = store.save(g, 1)
    assert d1.endswith(resilience.DELTA_SUFFIX), d1
    # per-rank expected state at step 1, LOCAL rows only: once the
    # peer is dead, collectives are off the table (rank_kill contract)
    mine = cells[g._proc_local_dev[g.plan.owner]]
    want_mine = np.asarray(g.get("v", mine)).copy()
    before = {}
    for p in (kf, d1):
        with open(p, "rb") as f:
            before[p] = f.read()
    coord.barrier("delta_chain_ready", timeout=60)

    g.run_steps(_sup_kernel, ["v"], ["v"], 1)
    dying = 1
    if args.phase in ("commit", "publish"):
        # the commit-side phases fire on the COMMITTING rank only;
        # re-point that role at the dying rank (killing the leader,
        # rank 0, would take the coordination service down — see
        # DELTA_KILL_PHASES)
        g._ckpt_writes_meta = args.rank == 0
        g._ckpt_commits = args.rank == dying
    if args.rank == dying:
        plan = faults.FaultPlan(seed=args.seed)
        plan.rank_death(phase=args.phase, rank=None)
        with plan:
            store.save(g, 2)  # raises InjectedRankDeath -> hard exit
        raise AssertionError(
            f"rank {args.rank} should have died at phase {args.phase}")
    try:
        store.save(g, 2)
        raise AssertionError("delta save completed despite a dead rank")
    except (coord.BarrierTimeoutError, coord.CheckpointCommitError):
        pass
    for p in (kf, d1):
        with open(p, "rb") as f:
            assert f.read() == before[p], \
                f"phase {args.phase} tore chain link {p}"
    assert resilience.verify_chain(d1)
    info = supervise.resume_latest(store_dir, _delta_schema(),
                                   load_balancing_method="block")
    assert info is not None and not info.salvaged
    assert info.step == 1, (args.phase, info.step)
    g3 = info.grid
    mine3 = g3.plan.cells[g3._proc_local_dev[g3.plan.owner]]
    np.testing.assert_array_equal(
        np.asarray(g3.get("v", mine3)), want_mine)


def _make_supervised(args, store, sleep_s=0.0, grid=None, start_step=0):
    """A SupervisedRunner over the harness grid whose step_fn reports
    progress to ``<store>/progress.rank<r>`` (the parent's cue for
    WHEN to deliver the real SIGTERM)."""
    from dccrg_tpu import supervise

    g = grid if grid is not None else _mk_grid(args.seed)
    prog = os.path.join(store, f"progress.rank{args.rank}")

    def step_fn(grid_, i):
        grid_.run_steps(_sup_kernel, ["v"], ["v"], 1)
        if sleep_s:
            time.sleep(sleep_s)
        with open(prog, "w") as f:
            f.write(str(i))

    sup = supervise.SupervisedRunner(
        g, step_fn, store, check_every=100, checkpoint_every=3,
        backoff=0.0, keep_last=16, grace=20.0, start_step=start_step,
        diagnostics_dir=store)
    return g, sup


def _write_digest(args, g, phase):
    import zlib

    from dccrg_tpu import checkpoint as checkpoint_mod

    cells = g.plan.cells
    h = f"{zlib.crc32(checkpoint_mod._replicated_pull(g, 'v', cells).tobytes()):08x}"
    hashes = _kv_allgather(f"preempt_{phase}", h, args.rank, args.procs)
    assert len(set(hashes)) == 1, hashes
    with open(os.path.join(args.store,
                           f"digest.{phase}.rank{args.rank}"), "w") as f:
        f.write(h)
    print(f"[rank {args.rank}] DIGEST preempt_{phase} {h}", flush=True)


def scenario_preempt_ref(args):
    """Phase 1: the uninterrupted supervised reference run."""
    g, sup = _make_supervised(args, args.store)
    sup.run(PREEMPT_STEPS)
    _write_digest(args, g, "ref")


def scenario_preempt_kill(args):
    """Phase 2: a REAL ``kill -TERM`` from the parent lands on rank 1
    mid-run. The per-step trip consensus makes EVERY rank observe the
    preemption at the same boundary, take the collective two-phase
    emergency checkpoint (shortened barrier timeouts) and raise
    PreemptedError — child_main maps it to the resumable exit code
    after re-verifying the checkpoint's CRC."""
    _g, sup = _make_supervised(args, args.store, sleep_s=0.4)
    sup.run(PREEMPT_STEPS)
    raise AssertionError(
        "run finished before the parent's SIGTERM landed; raise sleep_s")


def scenario_preempt_resume(args):
    """Phase 3: resume_latest picks the emergency checkpoint, the run
    completes to the reference step count, and every rank's final
    state must agree (the parent compares the digest with phase 1's
    bitwise)."""
    import jax.numpy as jnp

    from dccrg_tpu import supervise

    info = supervise.resume_latest(args.store, {"v": jnp.float32},
                                   load_balancing_method="block")
    assert info is not None, "no usable checkpoint to resume from"
    assert not info.salvaged and info.report.clean
    assert 0 < info.step < PREEMPT_STEPS, info.step
    g = info.grid
    g.update_copies_of_remote_neighbors()
    g, sup = _make_supervised(args, args.store, grid=g,
                              start_step=info.step)
    sup.run(PREEMPT_STEPS)
    assert sup.step == PREEMPT_STEPS
    _write_digest(args, g, "resume")


def scenario_trace_merge(args):
    """Telemetry tracing across 2 REAL ranks: each rank runs the same
    small loop (fused steps + halo refresh + one collective two-phase
    checkpoint) with tracing on, flushes its span ring to its own
    JSONL file, and rank 0 merges the per-rank files into one
    timeline — the events must carry the correct ``coord`` rank ids,
    come out wall-clock-ordered, include the step/exchange/save span
    names from EVERY rank, and the two ranks' collective-save spans
    must overlap in time (they synchronize on the same commit
    barriers)."""
    import numpy as np

    import jax.numpy as jnp

    from dccrg_tpu import coord, resilience, telemetry

    telemetry.configure(trace=True)
    telemetry.clear_trace()
    g = _mk_grid(args.seed)

    def kern(c, nbr, offs, mask):
        s = jnp.sum(jnp.where(mask, nbr["v"], jnp.float32(0)), axis=1)
        return {"v": jnp.float32(0.5) * c["v"] + jnp.float32(0.0625) * s}

    for _ in range(3):
        g.run_steps(kern, ["v"], ["v"], 1)
        g.update_copies_of_remote_neighbors()
    fn = os.path.join(args.tmp, "trace_ckpt.dc")
    resilience.save_checkpoint(g, fn)  # two-phase: real barriers
    path = os.path.join(args.tmp, f"trace_r{args.rank}.jsonl")
    n = telemetry.flush_trace(path)
    telemetry.configure(trace=False)
    assert n > 0, "no span events recorded with tracing on"
    coord.barrier("trace_flush", timeout=60)
    if args.rank == 0:
        paths = [os.path.join(args.tmp, f"trace_r{r}.jsonl")
                 for r in range(args.procs)]
        evs = telemetry.merge_traces(paths)
        ranks = {e["rank"] for e in evs}
        assert ranks == set(range(args.procs)), ranks
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts), "merged timeline not ts-ordered"
        assert all(float(e["dur"]) >= 0.0 for e in evs)
        for r in range(args.procs):
            names_r = {e["name"] for e in evs if e["rank"] == r}
            assert {"grid.step", "grid.exchange",
                    "ckpt.save"} <= names_r, (r, names_r)
        # the collective save really was collective: every rank's
        # last ckpt.save span STRICTLY overlaps every other's — the
        # two-phase commit's prepare/commit barriers hold all ranks
        # inside the save simultaneously (same host, shared
        # time.time() clock), so serialized saves would fail this
        last_saves = [
            [e for e in evs
             if e["rank"] == r and e["name"] == "ckpt.save"][-1]
            for r in range(args.procs)]
        lo = max(s["ts"] for s in last_saves)
        hi = min(s["ts"] + s["dur"] for s in last_saves)
        assert hi > lo, f"collective-save spans disjoint: {last_saves}"
        print(f"[rank 0] TRACE_MERGE {len(evs)} events, "
              f"ranks {sorted(ranks)}", flush=True)
    coord.barrier("trace_done", timeout=60)


# -- elastic multi-host fleet scenarios -------------------------------

FLEET_STEPS = 24


def _fleet_job_specs(seed: int, count: int, steps: int = FLEET_STEPS,
                     first: int = 0) -> list:
    """The deterministic job-parameter rows every rank (and the solo
    reference) builds its FleetJob objects from — job OBJECTS carry
    scheduler-mutated state, so each consumer constructs its own."""
    return [dict(name=f"fj{i}", length=(8, 8, 8), n_steps=int(steps),
                 params=(0.05,), seed=seed * 101 + i,
                 checkpoint_every=4)
            for i in range(first, first + count)]


def _fleet_jobs(specs) -> list:
    from dccrg_tpu.fleet import FleetJob

    return [FleetJob(**spec) for spec in specs]


def _solo_refs(specs) -> dict:
    """Uninterrupted single-host reference digests, computed from
    fresh job objects BEFORE any fleet serving (they share the solo
    compile; after a real kill the survivors race the coordination
    service's reaper, so the slow part runs up front)."""
    import jax

    from dccrg_tpu.fleet import run_solo

    dev = jax.local_devices()[0]
    return {spec["name"]: run_solo(f, device=dev) for spec, f in
            zip(specs, _fleet_jobs(specs))}


def _fleet_sched(args, jobs, store):
    import jax

    from dccrg_tpu import coord
    from dccrg_tpu.scheduler import FleetScheduler

    m = coord.Membership(args.rank, args.procs,
                         heartbeat_s=FLEET_HEARTBEAT_S,
                         lease_s=FLEET_LEASE_S)
    return FleetScheduler(store, jobs, quantum=4, membership=m,
                          devices=[jax.local_devices()[0]])


def _serve_fleet(args, sched, all_jobs, hook=None,
                 deadline_s: float = 120.0) -> bool:
    """Drive the rank-aware scheduler one tick at a time until every
    job (local or remote) has a report row, writing a progress file
    the parent cues its kill/stop signals from:
    ``ticks:done:total:reclaims``."""
    from dccrg_tpu import telemetry

    prog = os.path.join(args.tmp, f"fleet_progress.rank{args.rank}")
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        sched.run(max_ticks=sched.ticks + 1)
        if hook is not None:
            hook(sched)
        names = [j.name for j in all_jobs]
        done = sum(1 for n in names if n in sched.report)
        reclaims = int(telemetry.registry().counter_total(
            "dccrg_fleet_reclaims_total"))
        with open(prog, "w") as f:
            f.write(f"{sched.ticks}:{done}:{len(names)}:{reclaims}")
        if done == len(names) and getattr(hook, "complete", True):
            # a hook that still expects work (the rejoin wave-2 cue)
            # keeps the loop alive past a drained first wave
            return True
        time.sleep(0.02)
    return False


def _assert_fleet_solo(args, sched, specs, refs) -> None:
    """EVERY job — locally served, reclaimed, or reported done by a
    peer's marker — must carry the bitwise solo-reference digest."""
    for spec in specs:
        name = spec["name"]
        row = sched.report.get(name)
        assert row is not None and row["status"] == "done", (name, row)
        assert row["digest"] == refs[name], (
            name, row["digest"], refs[name])
        where = ("remote" if row.get("remote")
                 else f"rank{args.rank}")
        print(f"[rank {args.rank}] DIGEST fleet {name} "
              f"{row['digest']} ({where})", flush=True)


def scenario_host_death(args):
    """Child side of the host-death scenario (see module docstring):
    serve the shared job set rank-aware; rank 1 never returns (the
    parent's REAL ``kill -9`` lands once it reports progress); the
    survivors must reclaim its jobs within the lease bound and drain
    the whole fleet bitwise-solo."""
    os.environ["DCCRG_BARRIER_TIMEOUT"] = "5"
    specs = _fleet_job_specs(args.seed, count=4)
    refs = _solo_refs(specs)
    store = os.path.join(args.tmp, "fleet")
    os.makedirs(store, exist_ok=True)
    jobs = _fleet_jobs(specs)
    sched = _fleet_sched(args, jobs, store)
    ok = _serve_fleet(args, sched, jobs)
    assert ok, f"fleet did not drain: {sched.report}"
    _assert_fleet_solo(args, sched, specs, refs)
    # at least one job was reclaimed from the killed rank's stems
    # SOMEWHERE; each survivor asserts the global counter via its own
    # report (a reclaimed job shows requeues > 0 and is non-remote)
    reclaimed = [s["name"] for s in specs
                 if not sched.report[s["name"]].get("remote")
                 and sched.report[s["name"]]["requeues"] > 0]
    print(f"[rank {args.rank}] RECLAIMED {sorted(reclaimed)}",
          flush=True)


def _zombie_serve(args, specs, wave2_specs=None):
    """The shared body of zombie_fence / host_rejoin: serve with a
    drop-spy installed; rank 1 gets SIGSTOPped by the parent until a
    survivor reclaims its jobs, then SIGCONTed — its renew must fence
    with a typed OwnershipLostError. Returns (sched, fenced names,
    all job specs served)."""
    from dccrg_tpu.scheduler import OwnershipLostError

    os.environ["DCCRG_BARRIER_TIMEOUT"] = "5"
    store = os.path.join(args.tmp, "fleet")
    os.makedirs(store, exist_ok=True)
    jobs = _fleet_jobs(specs)
    all_specs = list(specs)
    sched = _fleet_sched(args, jobs, store)
    fenced = []
    orig_drop = sched._drop_lost

    def spy(batch, slot, job, err):
        assert isinstance(err, OwnershipLostError), err
        fenced.append(job.name)
        orig_drop(batch, slot, job, err)

    sched._drop_lost = spy
    all_jobs = list(jobs)
    hook = None
    if wave2_specs is not None:
        kv = sched.leases.kv
        wave1_names = [s["name"] for s in specs]
        added = []

        def hook(s):  # noqa: F811 - the rejoin wave-2 cue
            if added:
                return
            if args.rank == 0:
                st = s.membership.state(1)
                if st == "dead":
                    hook.saw_dead = True
                if (getattr(hook, "saw_dead", False) and st == "live"
                        and all(n in s.report for n in wave1_names)):
                    # rank 1 died, came back, and wave 1 drained:
                    # cue the second wave fleet-wide
                    kv.set("dccrg/wave2_go", "1")
            if kv.get("dccrg/wave2_go") is not None:
                for j in _fleet_jobs(wave2_specs):
                    s.add(j)
                    all_jobs.append(j)
                all_specs.extend(wave2_specs)
                added.append(True)
                hook.complete = True

        hook.complete = False

    ok = _serve_fleet(args, sched, all_jobs, hook=hook)
    assert ok, f"fleet did not drain: {sched.report}"
    return sched, fenced, all_specs


def scenario_zombie_fence(args):
    """Child side of the stale-owner fence (see module docstring)."""
    from dccrg_tpu import resilience, supervise

    specs = _fleet_job_specs(args.seed, count=4, steps=48)
    refs = _solo_refs(specs)
    sched, fenced, _ = _zombie_serve(args, specs)
    _assert_fleet_solo(args, sched, specs, refs)
    if args.rank == 1:
        assert fenced, "zombie rank was never fenced"
        print(f"[rank 1] FENCED {sorted(set(fenced))}", flush=True)
        store = os.path.join(args.tmp, "fleet")
        for name in sorted(set(fenced)):
            # the reclaimer's chain is intact — the zombie never
            # published over it
            entries = supervise.list_checkpoints(store, stem=name)
            assert entries, name
            newest = entries[0][1]  # list_checkpoints: newest first
            assert resilience.verify_chain(newest), name


def scenario_host_rejoin(args):
    """Child side of the elastic-regrow scenario (see module
    docstring): the zombie round trip, then a second wave the
    partition must hand the rejoined rank."""
    wave1 = _fleet_job_specs(args.seed, count=3, steps=48)
    wave2 = _fleet_job_specs(args.seed, count=args.procs, first=3)
    refs = _solo_refs(wave1 + wave2)
    sched, _fenced, all_specs = _zombie_serve(args, wave1,
                                              wave2_specs=wave2)
    assert len(all_specs) == len(wave1) + len(wave2), \
        "wave 2 was never cued"
    _assert_fleet_solo(args, sched, all_specs, refs)
    if args.rank == 1:
        local2 = [s["name"] for s in wave2
                  if not sched.report[s["name"]].get("remote")]
        assert local2, ("rejoined rank served no wave-2 job",
                        sched.report)
        print(f"[rank 1] REJOIN_SERVED {sorted(local2)}", flush=True)


def _mk_amr_grid(seed: int):
    """Like ``_mk_grid`` but REFINABLE (max level 1) — the distributed
    AMR scenarios need cells whose refinement the commit protocol can
    actually install."""
    import jax.numpy as jnp

    from dccrg_tpu.grid import Grid

    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((8, 8, 4))
         .set_periodic(True, True, False)
         .set_maximum_refinement_level(1)
         .set_neighborhood_length(1)
         .set_load_balancing_method("block")
         .initialize())
    cells = g.plan.cells
    g.set("v", cells, _expected(cells, seed))
    return g


def _amr_picks(g, rank: int, seed: int, count: int = 4):
    """``count`` still-refinable locally-owned cells of ``g``,
    seed-deterministic per rank (fuzz.py style)."""
    import numpy as np

    cells, owner = g.plan.cells, g.plan.owner
    lvl = g.mapping.get_refinement_level(cells)
    mask = g._proc_local_dev[owner] & (lvl < g.mapping.max_refinement_level)
    mine = cells[mask]
    rng = np.random.default_rng(seed * 1000 + rank)
    return sorted(int(c) for c in
                  rng.choice(mine, size=min(count, len(mine)),
                             replace=False))


def _amr_local_crc(g) -> int:
    """CRC of this rank's locally-owned payload — the bitwise
    'still serving the old plan' witness of the abort scenarios."""
    import zlib

    import numpy as np

    mine = g.plan.cells[g._proc_local_dev[g.plan.owner]]
    return zlib.crc32(np.asarray(g.get("v", mine)).tobytes())


def scenario_amr_commit(args):
    """Two distributed adapt epochs over the live coordination KV (see
    module docstring); epoch 2 exercises the background-build prepare
    path (DCCRG_BG_RECOMMIT=1)."""
    import numpy as np

    from dccrg_tpu import coord, distamr

    g = _mk_amr_grid(args.seed)
    assert g._multiproc, "harness grid must span processes"
    group = g.enable_distributed_amr(timeout=60)
    for epoch in (1, 2):
        picks = _amr_picks(g, args.rank, args.seed + epoch)
        for c in picks:
            g.refine_completely(c)
        if epoch == 2:
            os.environ["DCCRG_BG_RECOMMIT"] = "1"
        try:
            t0 = time.monotonic()
            new = g.stop_refining()
            dt = time.monotonic() - t0
        finally:
            os.environ.pop("DCCRG_BG_RECOMMIT", None)
        # every rank's requests landed: >= 8 children per LOCAL pick
        # alone (the fleet-wide set also carries the peers' children)
        assert len(new) >= 8 * len(picks), (len(new), picks)
        g.assign_children_from_parents(fields=["v"])
        g.clear_refined_unrefined_data()
        assert group.read_fence() == epoch, group.read_fence()
        dig = f"{distamr.plan_digest(g.plan):08x}"
        digs = _kv_allgather(f"amr_plan_{epoch}", dig, args.rank,
                             args.procs)
        assert len(set(digs)) == 1, f"plan diverged: {digs}"
        print(f"[rank {args.rank}] DIGEST amr_epoch{epoch} {dig}",
              flush=True)
        print(f"[rank {args.rank}] AMR_COMMIT_SECONDS epoch{epoch} "
              f"{dt:.3f}", flush=True)
    # unrefined original cells kept their payload bitwise through two
    # install/migrate rounds
    cells = g.plan.cells
    keep = cells[(g.mapping.get_refinement_level(cells) == 0)
                 & g._proc_local_dev[g.plan.owner]]
    np.testing.assert_array_equal(np.asarray(g.get("v", keep)),
                                  _expected(keep, args.seed))
    coord.barrier("amr_commit_done", timeout=60)


def scenario_amr_kill(args):
    """One REAL rank death at the ``--phase`` commit phase (see
    AMR_KILL_SITES); the survivor must abort typed within its barrier
    bound and keep serving the OLD plan bitwise. NO retry here: a
    surviving retry's install is a device-gather collective the dead
    peer can no longer join on a real gloo mesh — retry-over-survivors
    is pinned by tests/test_distamr.py with a scriptable membership
    view."""
    from dccrg_tpu import coord, distamr, faults, txn

    # tight bound: jax's coordination service hard-kills survivors
    # ~10s after a peer dies, so abort + asserts must finish first
    os.environ["DCCRG_BARRIER_TIMEOUT"] = "3"
    site, phase = AMR_KILL_SITES[args.phase]
    g = _mk_amr_grid(args.seed)
    group = g.enable_distributed_amr(timeout=3)
    picks = _amr_picks(g, args.rank, args.seed)
    for c in picks:
        g.refine_completely(c)
    pre_plan = distamr.plan_digest(g.plan)
    pre_bytes = _amr_local_crc(g)
    if args.rank == 1:
        plan = faults.FaultPlan(seed=args.seed)
        plan.rank_death(site=site, phase=phase, rank=None)
        with plan:
            g.stop_refining()  # InjectedRankDeath -> os._exit(DEATH_RC)
        raise AssertionError("rank 1 should have died mid-commit")
    try:
        g.stop_refining()
        raise AssertionError("commit decided with a dead rank")
    except txn.CrossRankAbortedError as e:
        assert isinstance(e.__cause__, coord.BarrierTimeoutError), \
            repr(e.__cause__)
    assert distamr.plan_digest(g.plan) == pre_plan, "plan changed"
    assert _amr_local_crc(g) == pre_bytes, "payload changed"
    assert sorted(g._refines) == picks, "requests not restored"
    assert group.read_fence() == 0, "fence moved without a commit"
    print(f"[rank {args.rank}] DIGEST amr_kill_{args.phase} "
          f"{pre_plan:08x}", flush=True)


def scenario_amr_zombie(args):
    """The stale proposer fence with a REAL stalled process (see
    module docstring). Rank 1 hangs inside propose past rank 0's
    barrier deadline (the parent layers a real SIGSTOP/SIGCONT round
    trip on the stall); rank 0 aborts typed, then advances the fence
    the way a re-formed fleet's commit would. The zombie must lose."""
    from dccrg_tpu import coord, distamr, faults, txn

    os.environ["DCCRG_BARRIER_TIMEOUT"] = "3"
    g = _mk_amr_grid(args.seed)
    group = g.enable_distributed_amr(
        timeout=(30 if args.rank == 1 else 3))

    def probe(phase, rank):  # the parent's SIGSTOP cue point
        with open(os.path.join(args.tmp, f"amr_phase.rank{rank}"),
                  "w") as f:
            f.write(phase)

    distamr._PHASE_PROBE = probe
    picks = _amr_picks(g, args.rank, args.seed)
    for c in picks:
        g.refine_completely(c)
    pre_plan = distamr.plan_digest(g.plan)
    pre_bytes = _amr_local_crc(g)

    if args.rank == 1:  # the zombie: stalls, wakes into a moved fence
        plan = faults.FaultPlan(seed=args.seed)
        plan.amr_hang(site="amr.propose", hang_s=6.0, rank=None)
        with plan:
            try:
                g.stop_refining()
                raise AssertionError("zombie finished the stale round")
            except txn.CrossRankAbortedError as e:
                assert isinstance(e.__cause__, coord.StaleFenceError), \
                    repr(e.__cause__)
        assert plan.fired("amr.propose.hang") == 1
        assert distamr.plan_digest(g.plan) == pre_plan
        assert _amr_local_crc(g) == pre_bytes
        assert sorted(g._refines) == picks
        print(f"[rank 1] FENCED amr fence={group.read_fence()}",
              flush=True)
        return
    # rank 0: the stall exhausts this rank's barrier bound
    try:
        g.stop_refining()
        raise AssertionError("commit decided without the stalled rank")
    except txn.CrossRankAbortedError as e:
        assert isinstance(e.__cause__, coord.BarrierTimeoutError), \
            repr(e.__cause__)
    assert distamr.plan_digest(g.plan) == pre_plan
    assert _amr_local_crc(g) == pre_bytes
    # stand in for the re-formed survivors' next commit: move the fence
    group.kv.set(group.fence_key(), "1")
    with open(os.path.join(args.tmp, "amr_zombie.fenced.rank0"),
              "w") as f:
        f.write("1")
    print(f"[rank 0] DIGEST amr_zombie {pre_plan:08x}", flush=True)
    # rank 0 is the jax.distributed LEADER: exiting now would take the
    # coordination service down mid-assertion on the zombie — wait for
    # its success marker (child_main writes it after the scenario)
    marker1 = os.path.join(args.tmp, "amr_zombie.rank1.ok")
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if os.path.exists(marker1):
            return
        time.sleep(0.1)
    raise AssertionError("zombie never finished its fence verdict")


def scenario_async_save(args):
    """The async (writer-thread) two-phase mp save on REAL ranks (see
    module docstring): bitwise vs a synchronous save, with real
    collectives dispatched and the LIVE grid mutated mid-write."""
    import zlib

    import numpy as np

    from dccrg_tpu import background, coord, resilience

    g = _mk_grid(args.seed)
    cells = g.plan.cells
    fn_sync = os.path.join(args.tmp, "sync.dc")
    resilience.save_checkpoint(g, fn_sync)
    assert resilience.verify_checkpoint(fn_sync) == []
    with open(fn_sync, "rb") as f:
        sync_crc = f"{zlib.crc32(f.read()):08x}"

    fn = os.path.join(args.tmp, "async.dc")
    frozen = background.freeze_grid_mp(g)
    assert frozen._ckpt_crc_via_kv, "mp freeze must take the gRPC CRC path"
    saver = background.AsyncSaver()
    saver.submit(lambda: resilience.save_checkpoint(frozen, fn))
    # the overlap the feature exists for: real cross-process
    # collectives from the MAIN thread while the writer saves
    for _ in range(3):
        g.update_copies_of_remote_neighbors()
    # and a LIVE mutation that must never reach the frozen bytes
    mine = cells[g._proc_local_dev[g.plan.owner]]
    g.set("v", mine, np.full(len(mine), -5.0, np.float32))
    saver.drain()
    assert resilience.verify_checkpoint(fn) == []
    with open(fn, "rb") as f:
        crc = f"{zlib.crc32(f.read()):08x}"
    assert crc == sync_crc, f"async bytes differ: {crc} != {sync_crc}"
    hashes = _kv_allgather("async_save_crc", crc, args.rank, args.procs)
    assert len(set(hashes)) == 1, hashes
    print(f"[rank {args.rank}] DIGEST async_save {crc}", flush=True)
    coord.barrier("async_save_done", timeout=60)


def scenario_async_save_kill(args):
    """A REAL rank death on rank 1's writer thread mid-slice (see
    module docstring): the drain surfaces it, the process exits hard;
    rank 0's writer aborts typed and the old checkpoint survives."""
    import numpy as np

    from dccrg_tpu import background, coord, faults, resilience

    os.environ["DCCRG_BARRIER_TIMEOUT"] = "3"
    g = _mk_grid(args.seed)
    cells = g.plan.cells
    fn = os.path.join(args.tmp, "kill.dc")
    resilience.save_checkpoint(g, fn)  # the good checkpoint
    with open(fn, "rb") as f:
        good = f.read()

    # new state that must never reach the final name
    mine = cells[g._proc_local_dev[g.plan.owner]]
    g.set("v", mine, np.full(len(mine), 123.0, np.float32))
    frozen = background.freeze_grid_mp(g)
    saver = background.AsyncSaver()
    if args.rank == 1:
        plan = faults.FaultPlan(seed=args.seed)
        plan.rank_death(phase="slice", rank=None)
        with plan:
            saver.submit(lambda: resilience.save_checkpoint(frozen, fn))
            saver.drain()  # re-raises InjectedRankDeath off the writer
        raise AssertionError("rank 1 should have died mid-slice")
    saver.submit(lambda: resilience.save_checkpoint(frozen, fn))
    try:
        saver.drain()
        raise AssertionError("async save completed despite a dead rank")
    except coord.BarrierTimeoutError as e:
        assert "save_commit" in e.tag or "save_prepare" in e.tag, e.tag
    with open(fn, "rb") as f:
        assert f.read() == good, "dead rank tore the old checkpoint"
    assert resilience.verify_checkpoint(fn) == []


def scenario_intake_kill(args):
    """The exactly-once admission proof with a REAL OS process death
    (see module docstring): rank 1 dies between the spool claim
    (intake lease + journal record durable in the coordination KV)
    and the scheduler add; rank 0 reclaims within the lease bound and
    drains every job bitwise-solo, exactly once."""
    import jax

    from dccrg_tpu import coord, faults, intake, telemetry
    from dccrg_tpu.scheduler import FleetScheduler

    os.environ["DCCRG_BARRIER_TIMEOUT"] = "5"
    specs = _fleet_job_specs(args.seed, count=4, steps=16)
    for s in specs:
        s["name"] = s["name"].replace("fj", "ij")
    names = [s["name"] for s in specs]
    refs = _solo_refs(specs)  # the slow compile, up front
    spool = os.path.join(args.tmp, "spool")  # shared by both ranks
    store = os.path.join(args.tmp, f"fleet.rank{args.rank}")
    os.makedirs(store, exist_ok=True)
    m = coord.Membership(args.rank, args.procs,
                         heartbeat_s=FLEET_HEARTBEAT_S,
                         lease_s=FLEET_LEASE_S)
    it = intake.StreamIntake(spool, membership=m,
                             lease_s=FLEET_LEASE_S, poll_s=0.02)
    sched = FleetScheduler(store, (), quantum=4, membership=m,
                           devices=[jax.local_devices()[0]],
                           intake=it)
    if args.rank == 1:
        # claim a spool record, then REALLY die between the claim and
        # the scheduler add (InjectedRankDeath -> os._exit(DEATH_RC))
        plan = faults.FaultPlan(seed=args.seed)
        plan.intake_death(rank=args.rank)
        with plan:
            t0 = time.monotonic()
            while time.monotonic() - t0 < 60:
                sched.run(max_ticks=sched.ticks + 1)
                time.sleep(0.02)
        raise AssertionError("rank 1 should have died at the claim")
    # rank 0: drop the records in, then HOLD until rank 1's claim is
    # durable (its journal record in the KV) so the death window is
    # guaranteed to open before this rank competes for admissions
    for spec in specs:
        intake.submit(spool, dict(
            name=spec["name"], length=list(spec["length"]),
            steps=spec["n_steps"], params=list(spec["params"]),
            seed=spec["seed"],
            checkpoint_every=spec["checkpoint_every"]))
    kv = m.kv
    claimed = None
    deadline = time.monotonic() + 60
    while claimed is None and time.monotonic() < deadline:
        for n in names:
            if kv.get(f"dccrg/intake/journal/{n}") is not None:
                claimed = n
                break
        time.sleep(0.05)
    assert claimed is not None, "rank 1 never claimed a spool record"
    # serve: the run-loop pump must reclaim the orphaned admission
    # (lease expiry + membership DEAD) and drain the whole fleet
    t0 = time.monotonic()
    while time.monotonic() - t0 < 90:
        sched.run(max_ticks=sched.ticks + 1)
        if all(n in sched.report for n in names) and it.idle():
            break
        time.sleep(0.02)
    assert all(n in sched.report for n in names), sched.report
    assert it.idle(), (it.backlog(), dict(it.leases.owned))
    _assert_fleet_solo(args, sched, specs, refs)
    # exactly once: the orphan was reclaimed (not re-submitted), every
    # admission happened on THIS rank exactly once, and each job's
    # terminal intake marker is in place
    assert it.reclaimed == 1, it.reclaimed
    admitted = int(telemetry.registry().counter_total(
        "dccrg_intake_admitted_total"))
    assert admitted == len(names), (admitted, names)
    for n in names:
        assert kv.get(f"dccrg/intake/done/{n}") is not None, n
    print(f"[rank {args.rank}] RECLAIMED ['{claimed}']", flush=True)


def scenario_rejoin_warm(args):
    """Child side of the rejoin_warm scenario (one single-rank phase
    per OS process; see the module docstring and _run_rejoin_warm):
    every phase serves the SAME three single-job buckets through the
    streaming-intake front door over the SAME persistent compile
    cache dir and prints its worst first-dispatch latency."""
    import jax

    from dccrg_tpu import coord, intake, telemetry, warmstart
    from dccrg_tpu.fleet import FleetJob
    from dccrg_tpu.scheduler import FleetScheduler

    phase = args.phase or "cold"
    cache = os.path.join(args.tmp, "warmcache")  # SHARED across phases
    os.environ["DCCRG_COMPILE_CACHE"] = cache
    os.environ["DCCRG_BARRIER_TIMEOUT"] = "5"
    # three DISTINCT single-job buckets: per-bucket demand is always
    # exactly one job, so every phase derives the same capacity (part
    # of the program key the warm pool must reproduce) regardless of
    # intake admission timing
    specs = [dict(name=f"wj{i}", length=ln, n_steps=16,
                  params=(0.05,), seed=args.seed * 131 + i,
                  checkpoint_every=4)
             for i, ln in enumerate(((8, 8, 8), (8, 8, 12),
                                     (12, 8, 8)))]
    names = [s["name"] for s in specs]
    bkeys = [FleetJob(**s).bucket_key() for s in specs]
    spool = os.path.join(args.tmp, f"spool.{phase}")
    store = os.path.join(args.tmp, f"fleet.{phase}")
    os.makedirs(store, exist_ok=True)
    m = coord.Membership(args.rank, args.procs,
                         heartbeat_s=FLEET_HEARTBEAT_S,
                         lease_s=FLEET_LEASE_S)
    it = intake.StreamIntake(spool, membership=m,
                             lease_s=FLEET_LEASE_S, poll_s=0.02)
    sched = FleetScheduler(store, (), quantum=4, membership=m,
                           devices=[jax.local_devices()[0]],
                           intake=it)
    pool = sched.warm
    assert pool is not None, "DCCRG_COMPILE_CACHE set but no pool"
    if phase != "cold":
        # the rejoin contract: the manifest survived the previous
        # process (kill -9 included) with ONLY complete records, and
        # the pre-compile sweep finishes BEFORE the serve clock starts
        assert pool._worker is not None and pool._worker.wait(120)
        assert pool._worker.error is None, pool._worker.error
        assert pool.errors == [], pool.errors
        assert all(pool.warm_ready(bk) for bk in bkeys), (
            sorted(pool.entries), bkeys)
    # spy on the scheduler's first-dispatch hook: ``seconds`` is the
    # measured dispatch latency — cold it carries the trace+compile,
    # warm it must not
    firsts = {}
    orig_note = pool.note_dispatch

    def _spy(batch, seconds):
        firsts.setdefault(batch.key, float(seconds))
        return orig_note(batch, seconds)

    pool.note_dispatch = _spy
    for spec in specs:
        intake.submit(spool, dict(
            name=spec["name"], length=list(spec["length"]),
            steps=spec["n_steps"], params=list(spec["params"]),
            seed=spec["seed"],
            checkpoint_every=spec["checkpoint_every"]))
    prog = os.path.join(args.tmp, f"rejoin_progress.{phase}")
    t0 = time.monotonic()
    while time.monotonic() - t0 < 120:
        sched.run(max_ticks=sched.ticks + 1)
        done = sum(1 for n in names if n in sched.report)
        with open(prog, "w") as f:
            f.write(f"{sched.ticks}:{done}:{len(names)}:0")
        if done == len(names) and it.idle():
            break
        time.sleep(0.02)
    assert all(n in sched.report for n in names), sched.report
    # the PR-17 intake saturation bounds across the churn window: the
    # backpressure gate never flapped and the spool fully drained
    assert it.gate_transitions == 0, it.gate_transitions
    assert it.idle() and it.oldest_age(it.clock()) == 0.0
    if phase != "cold":
        # every bucket's first dispatch was served from the pool
        reg = telemetry.registry()
        assert int(reg.counter_total(
            "dccrg_warm_hits_total")) == len(names), dict(firsts)
        assert int(reg.counter_total(
            "dccrg_warm_misses_total")) == 0, dict(firsts)
    ready = max(firsts.values())
    for n in names:
        print(f"[rank {args.rank}] DIGEST rejoin {n} "
              f"{sched.report[n]['digest']}", flush=True)
    print(f"[rank {args.rank}] READY {phase} {ready:.6f}", flush=True)
    if phase == "serve":
        # manifest-upsert churn: the parent's REAL kill -9 lands
        # somewhere in this loop — every iteration re-seals and
        # atomically replaces every record, so whatever instant the
        # SIGKILL picks, the next phase must find complete records
        n = 0
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            with pool._lock:
                for kid, e in list(pool.entries.items()):
                    rec = {k: v for k, v in e.items()
                           if not k.startswith("_")}
                    rec["hits"] = int(rec.get("hits", 0)) + 1
                    rec["last_hit"] = round(time.time(), 3)
                    warmstart.write_entry(pool.dir, kid, rec)
            n += 1
            with open(prog, "w") as f:
                f.write(
                    f"{sched.ticks}:{len(names)}:{len(names)}:{n}")
        raise AssertionError("serve phase outlived the parent SIGKILL")


CHILD_SCENARIOS = {
    "probe": scenario_probe,
    "save_restore": scenario_save_restore,
    "psum": scenario_psum,
    "barrier_timeout": scenario_barrier_timeout,
    "rank_kill": scenario_rank_kill,
    "consensus": scenario_consensus,
    "sdc_rank": scenario_sdc_rank,
    "preempt_ref": scenario_preempt_ref,
    "preempt_kill": scenario_preempt_kill,
    "preempt_resume": scenario_preempt_resume,
    "delta_restore": scenario_delta_restore,
    "delta_kill": scenario_delta_kill,
    "trace_merge": scenario_trace_merge,
    "host_death": scenario_host_death,
    "zombie_fence": scenario_zombie_fence,
    "host_rejoin": scenario_host_rejoin,
    "amr_commit": scenario_amr_commit,
    "amr_kill": scenario_amr_kill,
    "amr_zombie": scenario_amr_zombie,
    "async_save": scenario_async_save,
    "async_save_kill": scenario_async_save_kill,
    "intake_kill": scenario_intake_kill,
    "rejoin_warm": scenario_rejoin_warm,
}


def _marker(args) -> str:
    return os.path.join(args.tmp, f"{args.scenario}.rank{args.rank}.ok")


def child_main(args) -> int:
    from dccrg_tpu import faults, supervise

    try:
        _child_setup(args)
    except Exception as e:  # init failed: the parent probe maps to SKIP
        print(f"[rank {args.rank}] distributed init failed: {e}",
              flush=True)
        return SKIP_RC
    try:
        CHILD_SCENARIOS[args.scenario](args)
    except faults.InjectedRankDeath as e:
        # a REAL rank death: leave no trace, exit the OS process hard
        print(f"[rank {args.rank}] {e}", flush=True)
        os._exit(DEATH_RC)
    except supervise.PreemptedError as e:
        # preempted-but-resumable: the contract is a CRC-verified
        # emergency checkpoint plus the distinct exit code — every
        # rank must take this path, signaled or not (the consensus)
        from dccrg_tpu import resilience

        assert e.checkpoint, "preempted without a checkpoint"
        assert resilience.verify_checkpoint(e.checkpoint) == []
        print(f"[rank {args.rank}] PREEMPTED step={e.step} "
              f"ckpt={e.checkpoint} clean={e.clean}", flush=True)
        return e.exit_code
    # success marker BEFORE teardown: once a peer has died (rank_kill),
    # jax's coordination service hard-kills the survivors during exit —
    # the marker records that every assertion had already passed
    with open(_marker(args), "w") as f:
        f.write("ok")
    print(f"[rank {args.rank}] {args.scenario.upper()}_OK", flush=True)
    if args.scenario in PEER_DEATH_SCENARIOS and args.procs == 2:
        # the peer is a corpse and every assertion above has passed:
        # skip the graceful teardown that would block on a shutdown
        # barrier the dead rank can never join (see
        # PEER_DEATH_SCENARIOS) — burning the parent's whole per-leg
        # deadline per kill leg
        sys.stdout.flush()
        os._exit(0)
    return 0


# =====================================================================
# parent side: spawn, collect, judge
# =====================================================================

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(scenario: str, args, extra=()) -> list:
    port = _free_port()
    tmp = os.path.join(args.tmp, scenario)
    os.makedirs(tmp, exist_ok=True)
    for r in range(args.procs):  # retries must not see stale markers
        m = os.path.join(tmp, f"{scenario}.rank{r}.ok")
        if os.path.exists(m):
            os.unlink(m)
    procs = []
    for rank in range(args.procs):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--rank", str(rank), "--procs", str(args.procs),
             "--port", str(port), "--scenario", scenario,
             "--seed", str(args.seed), "--tmp", tmp] + list(extra),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO_ROOT))
    return procs


def _run_scenario(scenario: str, args, expect_rcs=None, extra=()) -> str:
    """Run one scenario across args.procs children; returns 'ok',
    'skip' or 'fail' and prints the children's transcripts on
    failure. NOTHING here can hang: every wait has a deadline and
    stragglers are killed."""
    procs = _spawn(scenario, args, extra=extra)
    deadline = time.monotonic() + args.timeout
    outs, rcs = [], []
    for p in procs:
        left = max(1.0, deadline - time.monotonic())
        try:
            out, _ = p.communicate(timeout=left)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<killed: scenario deadline>"
        outs.append(out)
        rcs.append(p.returncode)
    if any(rc == SKIP_RC for rc in rcs):
        return "skip"
    want = expect_rcs or [0] * args.procs
    tmp = os.path.join(args.tmp, scenario)
    ok = all(
        rc == w or (w == 0 and os.path.exists(
            os.path.join(tmp, f"{scenario}.rank{r}.ok")))
        for r, (rc, w) in enumerate(zip(rcs, want)))
    if not ok:
        print(f"--- {scenario}: rcs {rcs} (wanted {want}) " + "-" * 20)
        for r, out in enumerate(outs):
            print(f"--- rank {r} " + "-" * 40)
            print(out[-4000:])
    else:
        for out in outs:  # relay digests for determinism comparisons
            for line in out.splitlines():
                if (" DIGEST " in line
                        or " AMR_COMMIT_SECONDS " in line):
                    print(f"  {line}")
    return "ok" if ok else "fail"


def _collect(procs, deadline) -> tuple:
    """Deadline-bounded transcript/rc collection; stragglers are
    killed (NOTHING in the parent may hang)."""
    outs, rcs = [], []
    for p in procs:
        left = max(1.0, deadline - time.monotonic())
        try:
            out, _ = p.communicate(timeout=left)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<killed: scenario deadline>"
        outs.append(out)
        rcs.append(p.returncode)
    return outs, rcs


def _wait_progress(path, pred, deadline, procs=()) -> bool:
    """Poll a child progress file until ``pred(text)`` holds (or the
    deadline passes / every child already exited)."""
    while time.monotonic() < deadline:
        if procs and all(p.poll() is not None for p in procs):
            return False
        try:
            with open(path) as f:
                txt = f.read().strip()
            if txt and pred(txt):
                return True
        except (OSError, ValueError, IndexError):
            pass
        time.sleep(0.05)
    return False


def _dump_fail(scenario, outs, rcs, note="") -> None:
    print(f"--- {scenario}: rcs {rcs} {note} " + "-" * 20)
    for r, out in enumerate(outs):
        print(f"--- rank {r} " + "-" * 40)
        print(out[-4000:])


def _survivors_ok(scenario, args, rcs, skip_rank=None) -> bool:
    tmp = os.path.join(args.tmp, scenario)
    ok = True
    for r, rc in enumerate(rcs):
        if r == skip_rank:
            continue
        marker = os.path.join(tmp, f"{scenario}.rank{r}.ok")
        ok = ok and (rc == 0 or os.path.exists(marker))
    return ok


def _relay_digests(outs) -> None:
    for out in outs:
        for line in out.splitlines():
            if (" DIGEST " in line or " FENCED " in line
                    or " RECLAIMED " in line
                    or " REJOIN_SERVED " in line):
                print(f"  {line}")


def _run_host_death(args) -> str:
    """The elastic-fleet kill scenario: spawn the rank-aware fleet,
    wait until rank 1 reports REAL serving progress, deliver an
    actual ``kill -9`` (SIGKILL — no handler, no goodbye), and
    require every survivor to drain the whole fleet with bitwise-solo
    digests (their own asserts) within the deadline."""
    procs = _spawn("host_death", args)
    tmp = os.path.join(args.tmp, "host_death")
    deadline = time.monotonic() + args.timeout
    prog1 = os.path.join(tmp, "fleet_progress.rank1")
    killed = _wait_progress(
        prog1, lambda t: int(t.split(":")[0]) >= 3, deadline, procs)
    if killed:
        procs[1].kill()  # SIGKILL: a REAL dead host, mid-serve
    outs, rcs = _collect(procs, deadline)
    if any(rc == SKIP_RC for rc in rcs):
        return "skip"
    ok = killed and _survivors_ok("host_death", args, rcs, skip_rank=1)
    # the scenario's whole point is the kill->detect->reclaim path: a
    # survivor must report a NON-EMPTY reclaim (if the SIGKILL landed
    # while rank 1 happened to hold nothing, the run proved nothing)
    if ok and not any("RECLAIMED ['" in out for out in outs):
        ok = False
    if not ok:
        _dump_fail("host_death", outs, rcs,
                   f"(SIGKILL sent: {killed})")
        return "fail"
    _relay_digests(outs)
    return "ok"


def _run_stop_cont(scenario, args) -> str:
    """The zombie round trip shared by zombie_fence / host_rejoin:
    SIGSTOP rank 1 once it serves, wait until a SURVIVOR'S progress
    file shows a reclaim (lease expired -> CAS takeover), then
    SIGCONT it — the children assert the fence / regrow."""
    import signal as signal_mod

    procs = _spawn(scenario, args)
    tmp = os.path.join(args.tmp, scenario)
    deadline = time.monotonic() + args.timeout
    prog1 = os.path.join(tmp, "fleet_progress.rank1")
    stopped = resumed = False
    if _wait_progress(prog1, lambda t: int(t.split(":")[0]) >= 3,
                      deadline, procs):
        procs[1].send_signal(signal_mod.SIGSTOP)
        stopped = True
        # wait for reclaim evidence on any survivor (field 4 of the
        # progress line), bounded well below the scenario deadline
        def _reclaimed(txt):
            return int(txt.split(":")[3]) >= 1
        cue = time.monotonic() + 30.0
        got = False
        while time.monotonic() < min(cue, deadline) and not got:
            for r in range(args.procs):
                if r == 1:
                    continue
                p = os.path.join(tmp, f"fleet_progress.rank{r}")
                try:
                    with open(p) as f:
                        if _reclaimed(f.read().strip()):
                            got = True
                            break
                except (OSError, ValueError, IndexError):
                    pass
            time.sleep(0.05)
        procs[1].send_signal(signal_mod.SIGCONT)
        resumed = got
    outs, rcs = _collect(procs, deadline)
    if any(rc == SKIP_RC for rc in rcs):
        return "skip"
    ok = (stopped and resumed
          and _survivors_ok(scenario, args, rcs, skip_rank=None))
    if scenario == "zombie_fence" and ok:
        ok = any("FENCED" in out for out in outs)
    if scenario == "host_rejoin" and ok:
        ok = any("REJOIN_SERVED" in out for out in outs)
    if not ok:
        _dump_fail(scenario, outs, rcs,
                   f"(stopped: {stopped}, reclaim seen: {resumed})")
        return "fail"
    _relay_digests(outs)
    return "ok"


def _run_rejoin_warm(args) -> str:
    """The warm-rejoin proof (see module docstring): three sequential
    single-rank phases over one shared persistent compile-cache dir —
    cold baseline, a warm restart REALLY SIGKILLed mid-manifest-write,
    then the rejoin, whose worst first-dispatch latency must beat the
    cold baseline ≥10× with bitwise digest parity."""
    import re

    base = os.path.join(args.tmp, "rejoin_warm")
    pargs = argparse.Namespace(**vars(args))
    pargs.procs = 1  # each phase is one fresh single-rank process
    marker = os.path.join(base, "rejoin_warm.rank0.ok")

    def one(phase, kill=False):
        procs = _spawn("rejoin_warm", pargs, extra=("--phase", phase))
        deadline = time.monotonic() + args.timeout
        killed = False
        if kill:
            # wait until the manifest-upsert churn is demonstrably
            # running (field 4 of the progress line), then land a
            # REAL kill -9 mid-write-loop
            prog = os.path.join(base, f"rejoin_progress.{phase}")
            killed = _wait_progress(
                prog, lambda t: int(t.split(":")[3]) >= 25,
                deadline, procs)
            if killed:
                procs[0].kill()
        outs, rcs = _collect(procs, deadline)
        ok = (killed if kill
              else rcs[0] == 0 or os.path.exists(marker))
        return outs[0], rcs[0], ok

    def ready_of(out):
        m = re.search(r" READY \w+ ([0-9.]+)", out)
        return float(m.group(1)) if m else None

    def digests_of(out):
        return dict(re.findall(r" DIGEST rejoin (\S+) (\S+)", out))

    out_c, rc_c, ok_c = one("cold")
    if rc_c == SKIP_RC:
        return "skip"
    if not ok_c:
        _dump_fail("rejoin_warm[cold]", [out_c], [rc_c])
        return "fail"
    out_s, rc_s, ok_s = one("serve", kill=True)
    if rc_s == SKIP_RC:
        return "skip"
    if not ok_s:
        _dump_fail("rejoin_warm[serve]", [out_s], [rc_s],
                   "(SIGKILL never sent)")
        return "fail"
    out_w, rc_w, ok_w = one("warm")
    if rc_w == SKIP_RC:
        return "skip"
    cold, warm = ready_of(out_c), ready_of(out_w)
    dg_c, dg_w = digests_of(out_c), digests_of(out_w)
    if not ok_w or cold is None or warm is None:
        _dump_fail("rejoin_warm[warm]", [out_c, out_w], [rc_c, rc_w])
        return "fail"
    # the headline bound: first-dispatch-ready ≥10× faster warm than
    # cold, over a cache a kill -9 tore through mid-write
    if warm * 10.0 > cold:
        _dump_fail("rejoin_warm", [out_c, out_w], [rc_c, rc_w],
                   f"(warm {warm:.4f}s * 10 > cold {cold:.4f}s)")
        return "fail"
    if not dg_c or dg_c != dg_w:
        _dump_fail("rejoin_warm", [out_c, out_w], [rc_c, rc_w],
                   f"(digest parity: cold {dg_c} != warm {dg_w})")
        return "fail"
    _relay_digests([out_c, out_w])
    print(f"    rejoin_warm: cold {cold:.3f}s -> warm {warm:.4f}s "
          f"({cold / max(warm, 1e-9):.0f}x)")
    return "ok"


def _run_preempt_kill(args, store) -> str:
    """Phase 2 of the preempt scenario: spawn the children, wait until
    rank 1 reports real step progress, deliver an ACTUAL SIGTERM to
    it, and require EVERY rank (signaled or not — the consensus must
    spread the preemption) to exit with the resumable code 75."""
    import signal as signal_mod

    procs = _spawn("preempt_kill", args, extra=("--store", store))
    prog = os.path.join(store, "progress.rank1")
    deadline = time.monotonic() + args.timeout
    sent = False
    while not sent and time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs):
            break  # children already gone: transcripts tell the story
        try:
            with open(prog) as f:
                if int(f.read().strip() or "-1") >= 1:
                    procs[1].send_signal(signal_mod.SIGTERM)
                    sent = True
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    outs, rcs = [], []
    for p in procs:
        left = max(1.0, deadline - time.monotonic())
        try:
            out, _ = p.communicate(timeout=left)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<killed: scenario deadline>"
        outs.append(out)
        rcs.append(p.returncode)
    if any(rc == SKIP_RC for rc in rcs):
        return "skip"
    ok = sent and all(rc == RESUMABLE_RC for rc in rcs)
    if not ok:
        print(f"--- preempt_kill: rcs {rcs} (wanted all {RESUMABLE_RC}; "
              f"SIGTERM sent: {sent}) " + "-" * 12)
        for r, out in enumerate(outs):
            print(f"--- rank {r} " + "-" * 40)
            print(out[-4000:])
    return "ok" if ok else "fail"


def _run_delta(args) -> str:
    """The delta_rank_kill scenario (see module docstring): the
    restore/digest leg first, then one REAL rank death per two-phase
    delta-commit phase — prepare-side phases kill a slice writer,
    commit/publish kill the committing rank (re-pointed at rank 1;
    see DELTA_KILL_PHASES on why the leader must survive)."""
    v = _run_scenario("delta_restore", args)
    if v != "ok":
        return v
    for phase in DELTA_KILL_PHASES:
        expect = [DEATH_RC if r == 1 else 0
                  for r in range(args.procs)]
        v = _run_scenario("delta_kill", args, expect_rcs=expect,
                          extra=("--phase", phase))
        print(f"    delta_kill[{phase:<7}] {v}")
        if v != "ok":
            return v
    return "ok"


def _run_amr_kill(args) -> str:
    """The distributed-AMR kill loop: one REAL rank death per commit
    phase in AMR_KILL_PHASES (the death always lands on rank 1 —
    rank 0 is the jax.distributed leader, see DELTA_KILL_PHASES)."""
    for phase in AMR_KILL_PHASES:
        expect = [DEATH_RC if r == 1 else 0 for r in range(args.procs)]
        v = _run_scenario("amr_kill", args, expect_rcs=expect,
                          extra=("--phase", phase))
        print(f"    amr_kill[{phase:<7}] {v}")
        if v != "ok":
            return v
    return "ok"


def _run_amr_zombie(args) -> str:
    """amr_zombie with a REAL signal round trip layered on the
    in-child stall: SIGSTOP rank 1 once it reports the propose phase,
    SIGCONT it once rank 0 has advanced the fence. The injected hang
    alone already guarantees the zombie wakes into a moved fence —
    the signals make it an actually-stopped OS process meanwhile (the
    stop window stays well inside the coordination service's
    missed-heartbeat tolerance)."""
    import signal as signal_mod

    procs = _spawn("amr_zombie", args)
    tmp = os.path.join(args.tmp, "amr_zombie")
    deadline = time.monotonic() + args.timeout
    stopped = False
    if _wait_progress(os.path.join(tmp, "amr_phase.rank1"),
                      lambda t: t == "propose", deadline, procs):
        procs[1].send_signal(signal_mod.SIGSTOP)
        stopped = True
        _wait_progress(os.path.join(tmp, "amr_zombie.fenced.rank0"),
                       lambda t: t == "1", deadline, procs)
        procs[1].send_signal(signal_mod.SIGCONT)
    outs, rcs = _collect(procs, deadline)
    if any(rc == SKIP_RC for rc in rcs):
        return "skip"
    ok = stopped and _survivors_ok("amr_zombie", args, rcs)
    if ok:
        ok = any("FENCED" in out for out in outs)
    if not ok:
        _dump_fail("amr_zombie", outs, rcs, f"(stopped: {stopped})")
        return "fail"
    _relay_digests(outs)
    return "ok"


def _run_preempt(args) -> str:
    """The SIGTERM round trip (see module docstring): ref run, real
    mid-run kill of rank 1, resume — and the resumed digest must be
    bitwise identical to the uninterrupted reference's."""
    ref_store = os.path.join(args.tmp, "preempt_ref_store")
    store = os.path.join(args.tmp, "preempt_store")
    for d in (ref_store, store):
        os.makedirs(d, exist_ok=True)
    v = _run_scenario("preempt_ref", args, extra=("--store", ref_store))
    if v != "ok":
        return v
    v = _run_preempt_kill(args, store)
    if v != "ok":
        return v
    v = _run_scenario("preempt_resume", args, extra=("--store", store))
    if v != "ok":
        return v
    try:
        with open(os.path.join(ref_store, "digest.ref.rank0")) as f:
            ref = f.read()
        with open(os.path.join(store, "digest.resume.rank0")) as f:
            got = f.read()
    except OSError as e:
        print(f"preempt: digest files missing ({e})")
        return "fail"
    if ref != got:
        print(f"preempt: resumed digest {got} != uninterrupted {ref}")
        return "fail"
    return "ok"


def parent_main(args) -> int:
    scenarios = ([args.scenario] if args.scenario else list(SCENARIOS))
    args.tmp = os.path.join(args.tmp, f"run{os.getpid()}")  # no stale state
    os.makedirs(args.tmp, exist_ok=True)
    print(f"mp_harness: {args.procs} real jax.distributed CPU "
          f"processes, seed {args.seed}")
    verdict = _run_scenario("probe", args)
    if verdict != "ok":
        print("SKIP: this environment cannot run multi-process "
              "jax.distributed on CPU" if verdict == "skip"
              else "SKIP: probe failed (collectives unavailable)")
        return SKIP_RC
    print("  probe            ok (init + cross-process psum + barrier)")
    failed = []
    for sc in scenarios:
        expect = None
        run = _run_scenario
        if sc == "rank_kill":
            expect = [0] + [DEATH_RC] * (args.procs - 1)
        if sc == "preempt":  # parent-orchestrated three-phase round trip
            def run(_sc, args_, expect_rcs=None):  # noqa: ARG001
                return _run_preempt(args_)
        if sc == "delta_rank_kill":  # parent-orchestrated phase loop
            def run(_sc, args_, expect_rcs=None):  # noqa: ARG001
                return _run_delta(args_)
        if sc == "host_death":  # parent-orchestrated real SIGKILL
            def run(_sc, args_, expect_rcs=None):  # noqa: ARG001
                return _run_host_death(args_)
        if sc in ("zombie_fence", "host_rejoin"):
            def run(_sc, args_, expect_rcs=None, sc=sc):  # noqa: ARG001
                return _run_stop_cont(sc, args_)
        if sc == "amr_rank_kill":  # parent-orchestrated phase loop
            def run(_sc, args_, expect_rcs=None):  # noqa: ARG001
                return _run_amr_kill(args_)
        if sc == "amr_zombie":  # parent-orchestrated real SIGSTOP
            def run(_sc, args_, expect_rcs=None):  # noqa: ARG001
                return _run_amr_zombie(args_)
        if sc == "rejoin_warm":  # parent-orchestrated restart trio
            def run(_sc, args_, expect_rcs=None):  # noqa: ARG001
                return _run_rejoin_warm(args_)
        if sc in ("async_save_kill", "intake_kill"):
            expect = [DEATH_RC if r == 1 else 0
                      for r in range(args.procs)]
        verdict = run(sc, args, expect_rcs=expect)
        print(f"  {sc:<16} {verdict}")
        if verdict == "fail":
            failed.append(sc)
        elif verdict == "skip":  # init raced AFTER a good probe: retry
            verdict = run(sc, args, expect_rcs=expect)
            print(f"  {sc:<16} {verdict} (retry)")
            if verdict != "ok":
                failed.append(sc)
    if failed:
        print(f"FAILED: {failed}")
        return 1
    print("all scenarios passed")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--scenario", default=None,
                    choices=(None, "probe", "amr_kill") + SCENARIOS
                            + PREEMPT_PHASES + DELTA_LEGS)
    ap.add_argument("--store", default="",
                    help="shared checkpoint-store dir of the preempt "
                         "phases (parent-provided)")
    ap.add_argument("--phase", default="",
                    help="commit phase the delta_kill / amr_kill leg "
                         "injects the rank death at (parent-provided)")
    ap.add_argument("--seed", type=int, default=0,
                    help="deterministic data/fault seed (fuzz.py style)")
    ap.add_argument("--tmp", default=os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "dccrg_mp_harness"))
    ap.add_argument("--timeout", type=float, default=240.0,
                    help="per-scenario wall-clock bound (parent kills "
                         "stragglers)")
    args = ap.parse_args(argv)
    if args.child:
        return child_main(args)
    return parent_main(args)


if __name__ == "__main__":
    sys.exit(main())
