"""Elastic multi-host fleet: membership, lease ownership, fencing.

The pins, all single-process with fake clocks and an
:class:`~dccrg_tpu.coord.InMemoryKV` shared between in-process
'ranks' (the REAL multi-process proofs — an actual ``kill -9``, a
SIGSTOP zombie, a rejoin — live in tests/mp_harness.py):

- membership classification from observed lease age
  (live -> suspect -> dead -> live on rejoin) with the
  ``dccrg_fleet_membership{state}`` gauges, and a poll that NEVER
  blocks past its deadline even over a wedged KV store;
- a registered membership upgrades a barrier timeout into a typed
  :class:`~dccrg_tpu.coord.PeerDeadError` NAMING the dead rank;
- the lease/fencing edge cases: expiry exactly at a renew boundary,
  the reclaim-vs-late-renew race (epoch fencing wins), a
  double-reclaim by two survivors (KV compare-and-set: exactly one
  wins);
- the negative pins: the rank-unaware default constructs NO
  membership/lease machinery, and a rank-aware single-host scheduler
  produces bitwise-identical checkpoint files, job digests and
  reports to the plain scheduler;
- the in-process recovery flow: a dead 'rank' scheduler's jobs are
  reclaimed by the survivor, re-admitted from their checkpoint stems,
  and every job's final digest equals the uninterrupted solo run
  bitwise; a resumed zombie cannot publish (typed
  :class:`~dccrg_tpu.scheduler.OwnershipLostError`, chain intact);
- ``FaultPlan.host_death`` honored in-process at the scheduler tick
  boundary.
"""

import glob
import hashlib
import os
import time

import pytest

from dccrg_tpu import coord, resilience, telemetry
from dccrg_tpu.faults import FaultPlan, InjectedRankDeath
from dccrg_tpu.fleet import FleetJob, run_solo
from dccrg_tpu.scheduler import (FleetScheduler, JobLeases,
                                 OwnershipLostError, rank_aware_default)

pytestmark = pytest.mark.elastic


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("DCCRG_RANK_AWARE", raising=False)
    monkeypatch.delenv("DCCRG_HEARTBEAT_S", raising=False)
    monkeypatch.delenv("DCCRG_LEASE_S", raising=False)
    prev = coord.set_membership(None)
    # the registry is process-global: counters (reclaims per job name)
    # would otherwise leak across tests reusing the same job names
    telemetry.registry().reset()
    yield
    coord.set_membership(prev)
    telemetry.registry().reset()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


def _jobs(count=4, steps=8, **kw):
    return [FleetJob(f"ej{i}", length=(8, 8, 8), n_steps=steps,
                     params=(0.05,), seed=11 * i, checkpoint_every=2,
                     **kw)
            for i in range(count)]


def _solo_digests(count=4, steps=8):
    return {j.name: run_solo(j) for j in _jobs(count, steps)}


def _pair(tmp_path, kv, clock, count=4, steps=8, n_ranks=2,
          quantum=2):
    """Two in-process 'rank' schedulers over one shared dir + KV."""
    scheds = []
    for rank in range(n_ranks):
        m = coord.Membership(rank, n_ranks, kv=kv, heartbeat_s=1.0,
                             lease_s=4.0, clock=clock)
        scheds.append(FleetScheduler(
            str(tmp_path / "store"), _jobs(count, steps),
            quantum=quantum, membership=m))
    return scheds


def _tick(sched):
    sched.run(max_ticks=sched.ticks + 1)


# -- membership -------------------------------------------------------

def test_membership_classification_and_gauges():
    """live -> suspect -> dead from observed lease age; a resumed
    heartbeat flips back to live (elastic regrow); the state gauges
    export on every poll."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    a = coord.Membership(0, 2, kv=kv, heartbeat_s=1.0, lease_s=4.0,
                         clock=clk)
    b = coord.Membership(1, 2, kv=kv, heartbeat_s=1.0, lease_s=4.0,
                         clock=clk)
    a.heartbeat(force=True)
    b.heartbeat(force=True)
    assert a.poll() == {1: "live"}
    clk.advance(2.5)  # > suspect_s (2 heartbeats), < lease
    assert a.poll() == {1: "suspect"}
    clk.advance(2.0)  # past the lease bound
    assert a.poll() == {1: "dead"}
    assert a.dead_ranks() == [1] and a.live_ranks() == [0]
    assert a.detect_dead_ranks() == [1]
    b.heartbeat(force=True)  # the rank comes back
    assert a.poll() == {1: "live"}
    assert a.live_ranks() == [0, 1]
    reg = telemetry.registry()
    assert reg.gauges[("dccrg_fleet_membership",
                       (("state", "live"),))] == 2.0
    assert reg.gauges[("dccrg_fleet_membership",
                       (("state", "dead"),))] == 0.0
    assert reg.counter_value("dccrg_fleet_membership_transitions_total",
                             rank="1", state="dead") == 1


def test_membership_grace_for_slow_starters():
    """A peer that has NEVER heartbeat gets a full lease of grace
    from construction — a slow starter is not a corpse."""
    kv = coord.InMemoryKV()
    clk = FakeClock(100.0)
    a = coord.Membership(0, 2, kv=kv, heartbeat_s=1.0, lease_s=4.0,
                         clock=clk)
    assert a.poll() == {1: "live"}
    clk.advance(3.9)
    assert a.poll() == {1: "suspect"}  # aging, but inside the lease
    clk.advance(0.2)
    assert a.poll() == {1: "dead"}


def test_membership_poll_never_blocks():
    """A wedged KV read cannot block the step loop: the poll is
    deadline-bounded (run_with_deadline) and the previous view keeps
    aging instead."""
    class WedgedKV(coord.InMemoryKV):
        def get(self, key):
            time.sleep(5.0)
            return super().get(key)

    clk = FakeClock()
    a = coord.Membership(0, 2, kv=WedgedKV(), heartbeat_s=1.0,
                         lease_s=4.0, clock=clk)
    t0 = time.monotonic()
    states = a.poll(timeout=0.05)
    assert time.monotonic() - t0 < 2.0  # bounded, nowhere near 5 s
    assert states == {1: "live"}  # the stale (construction) view
    assert telemetry.registry().counter_value(
        "dccrg_membership_poll_failures_total") >= 1


def test_peer_dead_error_names_the_rank():
    """The detecting side of a host death: with a registered
    membership, a barrier raises a typed PeerDeadError naming the
    dead rank (still a BarrierTimeoutError — existing handlers keep
    working) instead of timing out and blaming the tag."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    a = coord.Membership(0, 2, kv=kv, heartbeat_s=1.0, lease_s=4.0,
                         clock=clk)
    clk.advance(10.0)
    a.poll()
    assert a.dead_ranks() == [1]
    coord.set_membership(a)
    try:
        with pytest.raises(coord.PeerDeadError) as ei:
            coord.barrier("elastic-test", timeout=0.5)
        assert ei.value.ranks == [1]
        assert "rank(s) [1]" in str(ei.value)
        assert isinstance(ei.value, coord.BarrierTimeoutError)
        assert ei.value.tag == "elastic-test"
    finally:
        coord.set_membership(None)
    # without the membership the same barrier is a plain no-op
    coord.barrier("elastic-test", timeout=0.5)


# -- lease / fencing edge cases ---------------------------------------

def test_lease_expiry_exactly_at_renew_boundary():
    """The contract at the boundary: age >= lease_s IS expired. A
    renew landing at exactly the lease bound races the reclaim, and
    the epoch fence decides — whoever CAS-creates the next epoch's
    claim key wins, the other side gets the typed error."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    owner = JobLeases(kv, 0, lease_s=4.0, clock=clk)
    obs = JobLeases(kv, 1, lease_s=4.0, clock=clk)
    owner.acquire("j")
    assert obs.expired_holder("j") is None  # fresh
    clk.advance(3.999)
    assert obs.expired_holder("j") is None  # still inside the lease
    clk.advance(0.001)  # age == lease_s exactly
    assert obs.expired_holder("j") == 0
    # the reclaim wins the boundary race...
    assert obs.try_reclaim("j") == 2
    # ...and the owner's same-instant renew is fenced, typed
    with pytest.raises(OwnershipLostError) as ei:
        owner.renew("j")
    assert ei.value.job == "j" and ei.value.held_epoch == 1
    assert "epoch 2" in str(ei.value.current)


def test_reclaim_vs_late_renew_race_fencing_wins():
    """The zombie's renew may even OVERWRITE the lease value after
    the reclaim — the claim key it can never un-create still convicts
    it before any publish."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    owner = JobLeases(kv, 0, lease_s=4.0, clock=clk)
    obs = JobLeases(kv, 1, lease_s=4.0, clock=clk)
    owner.acquire("j")
    assert obs.expired_holder("j") is None  # the watch starts here
    clk.advance(4.5)
    assert obs.expired_holder("j") == 0
    assert obs.try_reclaim("j") == 2
    # the zombie scribbles the lease VALUE directly (modeling the
    # worst-case write racing past the check)
    owner._write("j", 1)
    # the fencing gate still convicts it before any save publish
    with pytest.raises(OwnershipLostError):
        owner.check("j")
    assert "j" not in owner.owned  # forgotten locally
    # and the reclaimer still holds a verifiable claim
    assert obs.owned["j"] == 2
    obs.check("j")  # no raise


def test_double_reclaim_exactly_one_wins():
    """Two survivors observe the same expired epoch and race the
    takeover: the KV compare-and-set (create of the claim key) lets
    exactly one win; the loser returns None and backs off."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    owner = JobLeases(kv, 0, lease_s=4.0, clock=clk)
    s1 = JobLeases(kv, 1, lease_s=4.0, clock=clk)
    s2 = JobLeases(kv, 2, lease_s=4.0, clock=clk)
    owner.acquire("j")
    s1.expired_holder("j")  # both watches start at acquisition
    s2.expired_holder("j")
    clk.advance(9.0)
    assert s1.expired_holder("j") == 0
    assert s2.expired_holder("j") == 0
    wins = [s1.try_reclaim("j"), s2.try_reclaim("j")]
    assert sorted(w is not None for w in wins) == [False, True]
    winner = s1 if wins[0] is not None else s2
    loser = s2 if wins[0] is not None else s1
    assert winner.owned["j"] == 2
    assert "j" not in loser.owned


def test_orphaned_claim_is_escalated_past():
    """A reclaimer dying BETWEEN its claim-key CAS and the lease-
    record rewrite must not leave the job unreclaimable forever:
    after the orphaned claim has sat a full lease with the record
    unmoved, a survivor escalates past it to the next epoch."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    owner = JobLeases(kv, 0, lease_s=4.0, clock=clk)
    dying = JobLeases(kv, 1, lease_s=4.0, clock=clk)
    surv = JobLeases(kv, 2, lease_s=4.0, clock=clk)
    owner.acquire("j")
    surv.expired_holder("j")  # the survivor's watch starts here
    clk.advance(5.0)
    # the dying reclaimer wins the claim CAS... and dies before the
    # record rewrite (exactly the two-write window)
    assert kv.create(f"{dying.prefix}/j@2", "1")
    assert surv.expired_holder("j") == 0
    # first attempt: the claim is fresh — the claimant gets a full
    # lease of grace (it might be mid-rewrite)
    assert surv.try_reclaim("j") is None
    clk.advance(2.0)
    assert surv.try_reclaim("j") is None  # still inside the grace
    clk.advance(2.5)  # the orphaned claim aged a full lease
    assert surv.try_reclaim("j") == 3  # escalated past the orphan
    assert surv.owned["j"] == 3
    # the fence still convicts both the original owner and a resumed
    # claimant
    with pytest.raises(OwnershipLostError):
        owner.check("j")
    dying.owned["j"] = 2  # the claimant resumes believing it won
    with pytest.raises(OwnershipLostError):
        dying.check("j")


def test_finish_done_marker_is_fenced(tmp_path):
    """A fenced zombie completing a quantum must not write the done
    marker over the job a reclaimer is serving — _finish consults the
    same fencing gate as the save publishes."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    a, b = _pair(tmp_path, kv, clk, count=2, steps=8)
    for _ in range(2):
        clk.advance(0.5)
        _tick(a)
        _tick(b)
    a_jobs = sorted(a.leases.owned)
    assert a_jobs
    # b reclaims everything while a is paused
    for _ in range(20):
        clk.advance(0.6)
        _tick(b)
        if len(b.report) == 2:
            break
    assert len(b.report) == 2
    done_key = f"{b.leases.prefix}/done/{a_jobs[0]}"
    marker = kv.get(done_key)
    assert marker is not None and marker.startswith("done:1:")
    # the zombie wakes holding state at n_steps and tries to finish:
    # the fence drops the job instead of publishing a marker
    victim = a._by_name[a_jobs[0]]
    for batch, slot, job in a.active_jobs():
        if job is victim:
            a._finish(batch, slot, job)
            break
    assert victim.status == "lost"
    assert kv.get(done_key) == marker, "zombie overwrote the marker"


def test_acquire_adopts_own_record_and_rejects_foreign():
    """A restarted scheduler on the same rank adopts its own lease
    record; admission never steals a lease another rank holds (that
    is try_reclaim's job, gated on expiry)."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    a = JobLeases(kv, 0, lease_s=4.0, clock=clk)
    a.acquire("j")
    a2 = JobLeases(kv, 0, lease_s=4.0, clock=clk)  # same-rank restart
    assert a2.acquire("j") == 1
    b = JobLeases(kv, 1, lease_s=4.0, clock=clk)
    with pytest.raises(OwnershipLostError):
        b.acquire("j")


# -- negative pins ----------------------------------------------------

def _run_one(tmp_path, sub, **kw):
    d = tmp_path / sub
    sched = FleetScheduler(str(d), _jobs(3), quantum=2, **kw)
    report = sched.run()
    files = {}
    for p in sorted(glob.glob(os.path.join(str(d), "*"))):
        with open(p, "rb") as f:
            files[os.path.basename(p)] = hashlib.sha256(
                f.read()).hexdigest()
    return report, files


def test_rank_unaware_default_is_off_and_unchanged(tmp_path):
    """The negative pin, structural half: the default constructor
    builds NO membership/lease machinery (env unset), and the env
    knob parses as documented."""
    sched = FleetScheduler(str(tmp_path / "x"), [])
    assert sched.rank_aware is False
    assert sched.membership is None and sched.leases is None
    assert rank_aware_default() is False
    os.environ["DCCRG_RANK_AWARE"] = "1"
    try:
        assert rank_aware_default() is True
    finally:
        del os.environ["DCCRG_RANK_AWARE"]


def test_single_host_rank_aware_bitwise_pin(tmp_path):
    """The acceptance pin: rank-aware ON but single-process produces
    bitwise-identical checkpoint files, job digests and reports to
    the rank-unaware scheduler — and both match the solo baseline."""
    ref_report, ref_files = _run_one(tmp_path, "plain")
    m = coord.Membership(0, 1, kv=coord.InMemoryKV(), heartbeat_s=1.0,
                         lease_s=4.0, clock=FakeClock())
    aware_report, aware_files = _run_one(tmp_path, "aware",
                                         membership=m)
    solo = {j.name: run_solo(j) for j in _jobs(3)}
    for name, row in ref_report.items():
        assert row["digest"] == solo[name]
    # same decisions -> same rows (the aware run adds only the
    # owner_rank annotation) and bitwise-identical files
    for name in ref_report:
        aware = dict(aware_report[name])
        assert aware.pop("owner_rank") == 0
        assert aware == ref_report[name]
    assert aware_files == ref_files
    assert any(n.endswith(".dc") for n in ref_files)  # non-trivial


# -- the in-process recovery flow -------------------------------------

def test_reclaim_readmits_from_stem_bitwise(tmp_path):
    """A dead 'rank' scheduler's jobs are reclaimed by the survivor
    after the lease bound, re-admitted from their checkpoint stems,
    and EVERY job's final digest equals the uninterrupted solo run
    bitwise (victims included)."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    a, b = _pair(tmp_path, kv, clk)
    for _ in range(3):  # both serve: leases + stems established
        clk.advance(0.5)
        _tick(a)
        _tick(b)
    a_jobs = sorted(a.leases.owned)
    b_jobs = sorted(b.leases.owned)
    assert a_jobs and b_jobs, "partition left one rank idle"
    assert sorted(a_jobs + b_jobs) == [f"ej{i}" for i in range(4)]
    # rank 0 'dies': stop driving it; the survivor detects the lease
    # expiry + membership death and reclaims
    for _ in range(20):
        clk.advance(0.6)
        _tick(b)
        if len(b.report) == 4:
            break
    assert len(b.report) == 4, b.report
    solo = _solo_digests()
    for name, row in b.report.items():
        assert row["status"] == "done", (name, row)
        assert row["digest"] == solo[name], name
    reclaimed = [n for n in a_jobs
                 if not b.report[n].get("remote")
                 and b.report[n]["requeues"] > 0]
    assert sorted(reclaimed) == a_jobs
    assert telemetry.registry().counter_value(
        "dccrg_fleet_reclaims_total", job=a_jobs[0]) == 1


def test_zombie_owner_cannot_publish(tmp_path):
    """The resumed zombie: its renew raises the typed
    OwnershipLostError, the jobs drop locally WITHOUT touching a
    single file of the reclaimer's chain (verify_chain intact), and
    the zombie's next ticks serve nothing it no longer owns."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    a, b = _pair(tmp_path, kv, clk, steps=12)
    for _ in range(2):
        clk.advance(0.5)
        _tick(a)
        _tick(b)
    a_jobs = sorted(a.leases.owned)
    assert a_jobs
    # a pauses; b reclaims + finishes everything
    for _ in range(25):
        clk.advance(0.6)
        _tick(b)
        if len(b.report) == 4:
            break
    assert len(b.report) == 4
    store = str(tmp_path / "store")
    before = {}
    for p in sorted(glob.glob(os.path.join(store, "*"))):
        with open(p, "rb") as f:
            before[p] = f.read()
    # the zombie wakes: the fencing gate convicts it BEFORE any bytes
    # move (the epoch check precedes every save publish)
    with pytest.raises(OwnershipLostError):
        a.leases.check(a_jobs[0])
    clk.advance(0.1)
    _tick(a)  # renew_owned fences the rest; drops are side-effect-free
    for n in a_jobs:
        assert a._by_name[n].status in ("lost", "done"), (
            n, a._by_name[n].status)
    after = {}
    for p in sorted(glob.glob(os.path.join(store, "*"))):
        with open(p, "rb") as f:
            after[p] = f.read()
    assert before == after, "the zombie touched the reclaimer's files"
    from dccrg_tpu import supervise

    for n in a_jobs:
        newest = supervise.list_checkpoints(store, stem=n)[0][1]
        assert resilience.verify_chain(newest)
    # the zombie's own view converges through the done markers
    clk.advance(0.1)
    _tick(a)
    assert len(a.report) == 4
    for n in a_jobs:
        assert a.report[n]["status"] == "done"
        assert a.report[n].get("remote") and a.report[n]["owner_rank"] == 1


def test_rejoining_rank_reenters_partition(tmp_path):
    """Elastic regrow in-process: after being fenced out, the zombie
    rank heartbeats again, peers see it live, and NEWLY queued jobs
    partition onto it at the next tick."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    a, b = _pair(tmp_path, kv, clk, count=2, steps=4)
    for _ in range(12):
        clk.advance(0.6)
        _tick(a)
        _tick(b)
        if len(a.report) == 2 and len(b.report) == 2:
            break
    assert len(a.report) == 2 and len(b.report) == 2
    # a goes dark long enough to be declared dead...
    for _ in range(10):
        clk.advance(0.6)
        _tick(b)
    assert b.membership.state(0) == "dead"
    # ...then rejoins; the next wave lands on BOTH ranks
    wave2 = [FleetJob(f"w2_{i}", length=(8, 8, 8), n_steps=4,
                      params=(0.05,), seed=90 + i, checkpoint_every=2)
             for i in range(2)]
    for j in wave2:
        a.add(j)
    for j in [FleetJob(f"w2_{i}", length=(8, 8, 8), n_steps=4,
                       params=(0.05,), seed=90 + i,
                       checkpoint_every=2) for i in range(2)]:
        b.add(j)
    for _ in range(12):
        clk.advance(0.6)
        _tick(a)
        _tick(b)
        if all(f"w2_{i}" in a.report and f"w2_{i}" in b.report
               for i in range(2)):
            break
    assert b.membership.state(0) == "live"
    local_a = [n for n in ("w2_0", "w2_1")
               if not a.report[n].get("remote")]
    local_b = [n for n in ("w2_0", "w2_1")
               if not b.report[n].get("remote")]
    assert local_a and local_b, (local_a, local_b)
    assert sorted(local_a + local_b) == ["w2_0", "w2_1"]


def test_host_death_fault_fires_in_process(tmp_path):
    """FaultPlan.host_death honored at the scheduler tick boundary:
    the doomed rank raises InjectedRankDeath exactly at its tick; the
    survivor reclaims and drains the fleet."""
    kv = coord.InMemoryKV()
    clk = FakeClock()
    a, b = _pair(tmp_path, kv, clk)
    plan = FaultPlan(seed=3)
    plan.host_death(rank=0, at_tick=2)
    died = False
    with plan:
        for _ in range(4):
            clk.advance(0.5)
            try:
                _tick(a)
            except InjectedRankDeath:
                died = True
                break
            _tick(b)
    assert died and plan.fired("fleet.host") == 1
    with plan:  # rank 1's ticks never match the rank=0 rule
        for _ in range(22):
            clk.advance(0.6)
            _tick(b)
            if len(b.report) == 4:
                break
    assert len(b.report) == 4
    solo = _solo_digests()
    for name, row in b.report.items():
        assert row["status"] == "done" and row["digest"] == solo[name]
