"""Hybrid (refined-grid) plan construction vs the generic builder:
same row layout, semantically identical gather tables, identical
stencil results."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu import Grid
from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def make_grid(length=(6, 5, 4), periodic=(False, True, False), hood_len=1,
              n_dev=4, max_ref=2, partition="block", user_hood=None,
              refine=(1, 2, 3), unrefine=()):
    g = (
        Grid(cell_data={"v": jnp.float32})
        .set_initial_length(length)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(hood_len)
        .initialize(mesh_of(n_dev), partition=partition)
    )
    if user_hood is not None:
        g.add_neighborhood(42, user_hood)
    for c in refine:
        g.refine_completely(c)
    g.stop_refining()
    for c in unrefine:
        g.unrefine_completely(c)
    if unrefine:
        g.stop_refining()
    return g


def build_pair(monkeypatch, **kw):
    """Same refined grid via the hybrid path and the forced generic
    path."""
    hybrid = make_grid(**kw)
    monkeypatch.setenv("DCCRG_FORCE_GENERIC", "1")
    generic = make_grid(**kw)
    monkeypatch.delenv("DCCRG_FORCE_GENERIC")
    return hybrid, generic


def entry_sets(g, hid, table="of"):
    """Per-cell sets of (neighbor id, offset) from the gather tables —
    the padding-independent content."""
    plan = g.plan
    hood = plan.hoods[hid]
    if table == "of":
        rows, offs, mask = hood.merged_of_tables(plan.R - 1)
    else:
        rows, offs, mask = hood.to_rows, hood.to_offs, hood.to_mask
    out = {}
    for d in range(plan.n_dev):
        ids = np.concatenate([plan.local_ids[d], plan.ghost_ids[d]])
        for r, cid in enumerate(plan.local_ids[d]):
            entries = []
            for s in range(rows.shape[2]):
                if not mask[d, r, s]:
                    continue
                row = rows[d, r, s]
                nid = ids[row] if row < plan.L else ids[len(plan.local_ids[d]) + row - plan.L]
                entries.append((int(nid), tuple(int(x) for x in offs[d, r, s])))
            out[int(cid)] = sorted(entries)
    return out


CONFIGS = [
    dict(),
    dict(periodic=(True, True, True), length=(4, 4, 4), refine=(1, 64)),
    dict(hood_len=0),
    dict(hood_len=2, length=(5, 5, 5), n_dev=2, refine=(1, 62)),
    dict(n_dev=1),
    dict(partition="morton", refine=(1, 2, 9, 17)),
    dict(user_hood=[[1, 0, 0], [0, -1, 0], [1, 1, 1]]),
    dict(refine=(1,), unrefine=()),
    dict(length=(4, 4, 2), refine=(1, 2, 5), unrefine=(33,)),
]


@pytest.mark.parametrize("kw", CONFIGS)
def test_hybrid_matches_generic(monkeypatch, kw):
    hybrid, generic = build_pair(monkeypatch, **kw)
    np.testing.assert_array_equal(hybrid.plan.cells, generic.plan.cells)
    assert hybrid.plan.L == generic.plan.L
    assert hybrid.plan.R == generic.plan.R
    for d in range(hybrid.n_dev):
        np.testing.assert_array_equal(
            hybrid.plan.local_ids[d], generic.plan.local_ids[d]
        )
        np.testing.assert_array_equal(
            hybrid.plan.ghost_ids[d], generic.plan.ghost_ids[d]
        )
    for hid in hybrid.plan.hoods:
        hh, hg = hybrid.plan.hoods[hid], generic.plan.hoods[hid]
        assert entry_sets(hybrid, hid, "of") == entry_sets(generic, hid, "of")
        assert entry_sets(hybrid, hid, "to") == entry_sets(generic, hid, "to")
        np.testing.assert_array_equal(hh.send_rows, hg.send_rows)
        np.testing.assert_array_equal(hh.recv_rows, hg.recv_rows)
        if hid == DEFAULT_NEIGHBORHOOD_ID:
            np.testing.assert_array_equal(hh.n_inner, hg.n_inner)


def test_hybrid_deep_refinement(monkeypatch):
    """Two levels of refinement: easy level-1 cells inside the refined
    block, hard shells at both transitions."""
    kw = dict(length=(6, 6, 6), max_ref=2,
              refine=(1, 2, 3, 8, 9, 43, 44))
    hybrid, generic = build_pair(monkeypatch, **kw)
    # refine some children too (level-1 -> level-2)
    for g in (hybrid, generic):
        lvl1 = g.plan.cells[g.mapping.get_refinement_level(g.plan.cells) == 1]
        for c in lvl1[:8]:
            g.refine_completely(c)
        g.stop_refining()
    np.testing.assert_array_equal(hybrid.plan.cells, generic.plan.cells)
    hid = DEFAULT_NEIGHBORHOOD_ID
    assert entry_sets(hybrid, hid, "of") == entry_sets(generic, hid, "of")
    assert entry_sets(hybrid, hid, "to") == entry_sets(generic, hid, "to")


def test_hybrid_stencil_matches_generic(monkeypatch):
    """The split-table stencil (far pass + hard pass) must produce the
    same field values as the generic dense-table stencil."""
    from dccrg_tpu.models.advection_amr import AmrAdvection

    def run(force_generic):
        if force_generic:
            monkeypatch.setenv("DCCRG_FORCE_GENERIC", "1")
        else:
            monkeypatch.delenv("DCCRG_FORCE_GENERIC", raising=False)
        rng = np.random.default_rng(3)
        app = AmrAdvection(length=(8, 8, 1), max_refinement_level=1,
                           mesh=mesh_of(4))
        g = app.grid
        cells = g.get_cells()
        for c in cells[:6]:
            g.refine_completely(c)
        g.stop_refining()
        g.assign_children_from_parents(fields=["density"])
        g.clear_refined_unrefined_data()
        app._refresh_static()
        cells = g.get_cells()
        g.set("density", cells,
              rng.random(len(cells)).astype(np.float32))
        g.update_copies_of_remote_neighbors(fields=list(
            ("vx", "vy", "vz", "lx", "ly", "lz", "ilen", "density")))
        dt_s = 0.4 * app.max_time_step()
        app.step(dt_s)
        app.run_fused(3, dt_s)
        return g.get("density", g.get_cells())

    got = run(False)
    want = run(True)
    np.testing.assert_allclose(got, want, rtol=2e-6, atol=1e-7)


def test_to_tables_easy_cell_with_coarser_source(monkeypatch):
    """A 3x3x3 refined block makes its interior level-1 cells easy while
    edge cells keep coarser to-sources; the lazy to-tables must carry
    both the closed-form same-level entries and the cross-level ones
    (regression: cross-level entries used to overwrite slots [0, k))."""
    kw = dict(length=(8, 8, 8), max_ref=1, n_dev=2,
              refine=[1 + x + 8 * y + 64 * z
                      for x in range(3) for y in range(3) for z in range(3)])
    hybrid, generic = build_pair(monkeypatch, **kw)
    hid = DEFAULT_NEIGHBORHOOD_ID
    assert entry_sets(hybrid, hid, "to") == entry_sets(generic, hid, "to")


def test_sparse_user_hood_to_queries(monkeypatch):
    """Sparse user neighborhood [[2,0,0]]: finer to-sources originate
    from the unprobed +-1 slot (regression for the subset to-query's
    easy fast path)."""
    kw = dict(length=(8, 4, 4), max_ref=1, hood_len=2, n_dev=2,
              user_hood=[[2, 0, 0]], refine=(4,))
    hybrid, generic = build_pair(monkeypatch, **kw)
    for c in hybrid.plan.cells:
        assert hybrid.get_neighbors_to(c, 42) == generic.get_neighbors_to(c, 42), int(c)
    assert entry_sets(hybrid, 42, "to") == entry_sets(generic, 42, "to")
    assert entry_sets(hybrid, 42, "of") == entry_sets(generic, 42, "of")


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_randomized_adaptation_stress(monkeypatch, seed):
    """Random refine/unrefine/dont_* sequences: the hybrid plan must
    match the forced-generic plan after every commit, and the DEBUG
    verifiers must stay satisfied."""
    rng = np.random.default_rng(seed)
    dims = tuple(int(v) for v in rng.integers(3, 6, 3))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    n_dev = int(rng.choice([1, 2, 4, 5]))

    def build(force_generic):
        if force_generic:
            monkeypatch.setenv("DCCRG_FORCE_GENERIC", "1")
        else:
            monkeypatch.delenv("DCCRG_FORCE_GENERIC", raising=False)
        g = (Grid(cell_data={"v": jnp.float32})
             .set_initial_length(dims)
             .set_periodic(*periodic)
             .set_maximum_refinement_level(2)
             .initialize(mesh_of(n_dev)))
        local_rng = np.random.default_rng(seed + 100)
        for round_ in range(3):
            cells = g.plan.cells
            lvl = g.mapping.get_refinement_level(cells)
            for c in local_rng.choice(cells, size=min(5, len(cells)), replace=False):
                op = local_rng.integers(0, 4)
                if op == 0:
                    g.refine_completely(int(c))
                elif op == 1:
                    g.unrefine_completely(int(c))
                elif op == 2:
                    g.dont_refine(int(c))
                else:
                    g.dont_unrefine(int(c))
            g.stop_refining()
            g.clear_refined_unrefined_data()
        return g

    hybrid = build(False)
    generic = build(True)
    np.testing.assert_array_equal(hybrid.plan.cells, generic.plan.cells)
    np.testing.assert_array_equal(hybrid.plan.owner, generic.plan.owner)
    hid = DEFAULT_NEIGHBORHOOD_ID
    assert entry_sets(hybrid, hid, "of") == entry_sets(generic, hid, "of")
    assert entry_sets(hybrid, hid, "to") == entry_sets(generic, hid, "to")
    np.testing.assert_array_equal(hybrid.plan.hoods[hid].send_rows,
                                  generic.plan.hoods[hid].send_rows)
    # DEBUG verifiers on the hybrid result
    from dccrg_tpu import verify as _verify
    _verify.is_consistent(hybrid)
    _verify.verify_neighbors(hybrid)
    _verify.verify_remote_neighbor_info(hybrid)
    # exchange still correct
    cells = hybrid.plan.cells
    hybrid.set("v", cells, cells.astype(np.float32))
    hybrid.update_copies_of_remote_neighbors()
    host = np.asarray(hybrid.data["v"])
    for d in range(hybrid.n_dev):
        for r, cid in enumerate(hybrid.plan.ghost_ids[d]):
            assert host[d, hybrid.plan.L + r] == float(cid)
