#!/bin/sh
# Real multi-process CI leg: the 2-process jax.distributed CPU smoke
# harness (tests/mp_harness.py) — save/restore through the two-phase
# commit with REAL barriers and the REAL cross-rank CRC all-gather,
# _replicated_pull psum consistency, barrier-timeout, rank-kill
# recovery, distributed trip consensus, the sdc_rank scenario (a
# FINITE bit-flip on one real rank -> consensus CORRUPT trip on all
# ranks, collective rollback, bitwise reconvergence), the SIGTERM
# round trip
# (a REAL kill -TERM of one rank mid-run: every rank must take the
# collective emergency checkpoint, exit with the resumable code 75,
# and supervise.resume_latest must reconverge bitwise), and the
# incremental-checkpoint delta_rank_kill scenario (keyframe+delta
# chains through the real two-phase commit, a REAL rank death at
# every delta-commit phase, chain-aware resume digest-compared with
# an uninterrupted run), plus the telemetry trace_merge scenario
# (rank-tagged span traces from 2 real ranks — steps, halo
# exchanges, the collective two-phase save — merged into one
# coherent wall-clock timeline). Complements the faked splits of
# tests/test_multiprocess.py (which run in tier-1) with actual OS
# processes.
#
# The distributed-AMR scenarios (amr_commit / amr_rank_kill /
# amr_zombie: epoch-fenced collective structure commits over the live
# coordination KV, a REAL rank death at each commit phase, a REAL
# SIGSTOPped zombie proposer losing to the fence) and the async
# writer-thread mp-save scenarios (async_save / async_save_kill) ride
# the default 2-process sweep, as do the streaming-intake intake_kill
# scenario and the warm-start rejoin_warm trio (a cold baseline, a
# warm restart REALLY SIGKILLed mid-manifest-write, then a rejoin
# over the same persistent compile cache that must be >=10x faster to
# first dispatch with bitwise digest parity). The single-process
# dist-AMR fuzz leg
# below additionally sweeps injected aborts at EVERY protocol phase —
# including "prepare", which no real-process kill can cover (a
# survivor inside the prepare device gather blocks in the gloo
# collective when its peer dies).
#
# Skips cleanly (exit 0, with a notice) where jax.distributed on CPU
# is unavailable — the harness probes the environment first and exits
# 77 in that case. Seeds are deterministic (fuzz.py style): pass
# --seed N to replay a run byte-identically.
#
# The elastic-fleet scenarios (host_death / zombie_fence /
# host_rejoin: rank-aware FleetScheduler, membership heartbeat
# leases, epoch-fenced job reclaim) run in the default 2-process
# sweep above AND again at 3 REAL processes below — a 3-host fleet is
# the smallest one where the reclaim race (two survivors, one CAS
# winner) is real.
#
# Usage: tests/ci_mp_leg.sh [extra mp_harness args, e.g. --seed 3]
set -e
cd "$(dirname "$0")/.."
rc=0
python tests/mp_harness.py --procs 2 "$@" || rc=$?
if [ "$rc" = "77" ]; then
    echo "ci_mp_leg: SKIP (jax.distributed unavailable on CPU here)"
    exit 0
fi
if [ "$rc" != "0" ]; then
    exit $rc
fi
for sc in host_death zombie_fence host_rejoin; do
    rc=0
    python tests/mp_harness.py --procs 3 --scenario "$sc" "$@" || rc=$?
    if [ "$rc" = "77" ]; then
        echo "ci_mp_leg: SKIP 3-proc $sc (jax.distributed unavailable)"
        rc=0
    fi
    if [ "$rc" != "0" ]; then
        exit $rc
    fi
done
# single-process dist-AMR fuzz: N faked ranks' full protocol rounds
# (commit parity + injected aborts at every phase, prepare included)
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m dccrg_tpu.fuzz --dist-amr 2
exit 0
