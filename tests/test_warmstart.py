"""Warm-start resilience tests (dccrg_tpu/warmstart.py).

Everything here is tier-1: single process, CPU, tmp-dir cache
directories. The persistent compile-cache manifest's crash
consistency (round-trip, torn/corrupt conviction + quarantine,
cache-epoch drift rejection), the pre-warmed bucket pool's bitwise
parity with the ordinary jit path (the AOT-served program and a
prewarm-vs-dispatch race both produce byte-identical digests), the
SLO projection's cold-compile charge, the full injected fault matrix
over ``WARMSTART_FAULT_SITES`` (every damage class degrades to cold
with a typed error — no crash, no wrong program, no silent warm
claim), retention GC bounds, and the journaled decision replay. The
REAL kill -9 rejoin proof (first-dispatch-ready >=10x faster warm
vs cold over the same cache dir) is the ``rejoin_warm`` scenario in
tests/mp_harness.py via ci_mp_leg.sh.

The negative pin: with ``DCCRG_COMPILE_CACHE`` unset no pool exists
(``sched.warm is None``, ``warmstart.active() is None``) and serving
is bitwise identical to a cache-dir run's digests.
"""

import json
import os
import threading

import pytest

from dccrg_tpu import coord, faults, fleet, telemetry, warmstart
from dccrg_tpu.autopilot import (RULES, Autopilot, key_id,
                                 read_journal, replay)
from dccrg_tpu.fleet import FleetJob
from dccrg_tpu.scheduler import FleetScheduler
from dccrg_tpu.warmstart import WarmCacheError, WarmPool

pytestmark = pytest.mark.warmstart


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Warm-start knobs out of the env, a fresh telemetry registry
    and program cache, no leaked active pool — and again on the way
    out (registry, program cache and pool are process-global)."""
    for var in ("DCCRG_COMPILE_CACHE", "DCCRG_WARM_POOL",
                "DCCRG_WARM_GC_BYTES", "DCCRG_WARM_GC_AGE_S",
                "DCCRG_AUTOPILOT", "DCCRG_DECISION_FILE"):
        monkeypatch.delenv(var, raising=False)
    telemetry.registry().reset()
    fleet._FLEET_PROGRAMS.clear()
    warmstart.deactivate()
    yield
    warmstart.deactivate()
    fleet._FLEET_PROGRAMS.clear()
    telemetry.registry().reset()


def _jobs(n=2, steps=6, **kw):
    return [FleetJob(f"j{i}", length=(8, 8, 8), n_steps=steps,
                     seed=i, checkpoint_every=4, **kw)
            for i in range(n)]


def _serve(tmp_path, sub, jobs, pool=None):
    sched = FleetScheduler(str(tmp_path / sub), jobs,
                           warm_pool=pool)
    report = sched.run()
    assert {r["status"] for r in report.values()} == {"done"}
    return report


def _digests(report):
    return {n: r["digest"] for n, r in report.items()}


def _seed_manifest(d, compile_s=2.5, hits=1, last_hit=1000.0,
                   capacity=8, job=None):
    """Land one well-formed manifest record by hand (the shape
    note_dispatch writes) and return its kid."""
    job = job or _jobs(1)[0]
    bk = job.bucket_key()
    kid = key_id((bk, capacity))
    warmstart.ensure_cache(d)
    warmstart.write_entry(str(d), kid, {
        "key": warmstart.bucket_payload(bk), "capacity": capacity,
        "integrity": False, "bulk": False, "hits": hits,
        "last_hit": last_hit, "compile_s": compile_s})
    return kid, bk


# -- manifest crash consistency ---------------------------------------

def test_manifest_record_roundtrip(tmp_path):
    d = str(tmp_path / "cache")
    kid, bk = _seed_manifest(d, compile_s=1.25, hits=3)
    rec = warmstart.read_entry(warmstart.entry_path(d, kid))
    assert rec["_kid"] == kid
    assert rec["_bucket"] == bk
    assert rec["hits"] == 3 and rec["compile_s"] == 1.25
    assert rec["epoch"] == warmstart.cache_epoch()
    entries, rejects = warmstart.load_manifest(d)
    assert list(entries) == [kid] and rejects == []
    # the payload<->tuple round trip is exact
    assert warmstart.bucket_from_payload(
        warmstart.bucket_payload(bk)) == bk
    # and the reconstructed job proves itself by re-deriving the key
    assert warmstart.job_for_bucket(bk).bucket_key() == bk


def test_concurrent_writers_last_complete_wins(tmp_path):
    """Two ranks upserting the same kid: per-entry atomic rename
    means the last complete write is what every reader sees — never
    a torn interleaving."""
    d = str(tmp_path / "cache")
    kid, _bk = _seed_manifest(d, hits=1)
    _seed_manifest(d, hits=7)  # the second writer
    rec = warmstart.read_entry(warmstart.entry_path(d, kid))
    assert rec["hits"] == 7
    assert len(os.listdir(os.path.join(d, "manifest"))) == 1


def test_callable_kernels_never_manifest():
    """An identity-bucketed callable cannot survive a restart — its
    bucket key has no durable spelling, so it is simply never
    manifested (stays cold, no wrong-program risk)."""
    job = FleetJob("c", length=(8, 8, 8),
                   kernel=lambda cell, nbr, offs, mask, k: cell)
    assert warmstart.bucket_payload(job.bucket_key()) is None


def test_registry_drift_is_typed(tmp_path):
    """A manifested bucket key whose kernel no longer reconstructs
    (renamed/removed from the registry) is a typed WarmCacheError —
    prewarm degrades it to cold instead of compiling a wrong
    program."""
    bk = _jobs(1)[0].bucket_key()
    drifted = bk[:4] + ("no-such-kernel",) + bk[5:]
    with pytest.raises(WarmCacheError):
        warmstart.job_for_bucket(drifted)


def test_torn_record_convicted_and_quarantined(tmp_path):
    d = str(tmp_path / "cache")
    plan = faults.FaultPlan()
    plan.warm_torn_manifest()
    with plan:
        kid, _bk = _seed_manifest(d)
    assert plan.fired("warm.manifest.write.torn") == 1
    with pytest.raises(WarmCacheError, match="torn"):
        warmstart.read_entry(warmstart.entry_path(d, kid))
    pool = WarmPool(d, start_pool=False)
    assert pool.entries == {}
    assert [k for k, _e in pool.errors] == [kid]
    assert isinstance(pool.errors[0][1], WarmCacheError)
    # quarantined out of the manifest: the next load is clean
    assert os.listdir(os.path.join(d, "manifest")) == []
    assert os.listdir(os.path.join(d, "quarantine")) == [
        kid + ".rec"]
    assert warmstart.load_manifest(d) == ({}, [])
    pool.close()


def test_corrupt_entry_convicted_and_quarantined(tmp_path):
    d = str(tmp_path / "cache")
    plan = faults.FaultPlan()
    plan.warm_corrupt_entry()
    with plan:
        kid, _bk = _seed_manifest(d)
    with pytest.raises(WarmCacheError):
        warmstart.read_entry(warmstart.entry_path(d, kid))
    pool = WarmPool(d, start_pool=False)
    assert pool.entries == {} and len(pool.errors) == 1
    assert os.listdir(os.path.join(d, "quarantine")) == [
        kid + ".rec"]
    pool.close()


def test_version_drift_rejected_to_cold(tmp_path):
    """A record stamped with a different cache epoch (another
    jax/jaxlib/package stack) is REJECTED — the frame is intact, the
    bytes parse, and it is still never trusted."""
    d = str(tmp_path / "cache")
    plan = faults.FaultPlan()
    plan.warm_stale_epoch()
    with plan:
        kid, _bk = _seed_manifest(d)
    with pytest.raises(WarmCacheError, match="epoch drift"):
        warmstart.read_entry(warmstart.entry_path(d, kid))
    pool = WarmPool(d, start_pool=False)
    assert pool.entries == {} and len(pool.errors) == 1
    assert not pool._ready
    pool.close()


# -- the warm pool ----------------------------------------------------

def test_cold_run_manifests_and_warm_run_hits(tmp_path):
    """The headline path: a cold run records its bucket key; a fresh
    pool over the same dir pre-compiles it and the next run's first
    dispatch is served warm — byte-identical digests throughout."""
    d = str(tmp_path / "cache")
    pool = WarmPool(d, start_pool=False)
    ref = _digests(_serve(tmp_path, "ck-cold", _jobs(), pool))
    pool.close()
    entries, rejects = warmstart.load_manifest(d)
    assert len(entries) == 1 and rejects == []
    (rec,) = entries.values()
    assert rec["hits"] == 1 and rec["compile_s"] > 0.0
    assert telemetry.registry().counter_total(
        "dccrg_warm_misses_total") == 1

    fleet._FLEET_PROGRAMS.clear()  # a fresh process boundary
    pool2 = WarmPool(d, start_pool=False)
    pool2.prewarm(block=True)
    assert pool2.errors == []
    assert len(pool2._ready) == 1
    warm = _digests(_serve(tmp_path, "ck-warm", _jobs(), pool2))
    assert warm == ref  # bitwise: the AOT program IS the jit program
    assert pool2._served  # no silent warm claim: it really served
    assert telemetry.registry().counter_total(
        "dccrg_warm_hits_total") == 1
    # the manifest learned: hit counter bumped, compile cost kept
    rec2 = warmstart.read_entry(
        warmstart.entry_path(d, rec["_kid"]))
    assert rec2["hits"] == 2
    assert rec2["compile_s"] == rec["compile_s"]
    # first-dispatch-ready gauge published
    assert telemetry.registry().gauges[
        ("dccrg_warm_first_dispatch_ready_seconds", ())] > 0.0
    pool2.close()


def test_negative_pin_no_cache_no_pool(tmp_path):
    """DCCRG_COMPILE_CACHE unset: no pool is constructed, the
    serving loop takes zero new branches, no warm metric moves, and
    digests are bitwise identical to a cache-dir run's."""
    assert WarmPool.from_env() is None
    sched = FleetScheduler(str(tmp_path / "ck-none"), _jobs())
    report = sched.run()
    assert sched.warm is None
    assert sched.slo.warm_cost is None
    assert warmstart.active() is None
    assert warmstart.take_prewarmed(("any",)) is None
    reg = telemetry.registry()
    for name in ("dccrg_warm_hits_total", "dccrg_warm_misses_total",
                 "dccrg_warm_decisions_total",
                 "dccrg_warm_cache_errors_total"):
        assert reg.counter_total(name) == 0
    fleet._FLEET_PROGRAMS.clear()
    telemetry.registry().reset()
    pool = WarmPool(str(tmp_path / "cache"), start_pool=False)
    with_cache = _serve(tmp_path, "ck-cache", _jobs(), pool)
    pool.close()
    assert _digests(report) == _digests(with_cache)


def test_prewarm_vs_dispatch_race_is_bitwise_neutral(tmp_path):
    """The background prewarm thread racing live dispatches: whether
    a bucket's program comes from the pool or is built by the
    dispatch that loses the race, the digests are byte-identical and
    nothing deadlocks."""
    d = str(tmp_path / "cache")
    jobs = _jobs(3)
    pool = WarmPool(d, start_pool=False)
    ref = _digests(_serve(tmp_path, "ck-a", jobs, pool))
    pool.close()
    fleet._FLEET_PROGRAMS.clear()
    pool2 = WarmPool(d, start_pool=False)
    worker = pool2.prewarm()  # threaded: races the serve below
    try:
        got = _digests(_serve(tmp_path, "ck-b", _jobs(3), pool2))
        assert got == ref
        assert worker.wait(30.0)
        assert worker.error is None
    finally:
        worker.stop()
        pool2.close()


def test_prewarm_worker_is_abortable(tmp_path):
    d = str(tmp_path / "cache")
    _seed_manifest(d)
    pool = WarmPool(d, start_pool=False)
    # abort set before the sweep starts: it must exit promptly
    # without compiling anything
    ev = threading.Event()
    ev.set()
    pool._prewarm_run(ev)
    assert pool._ready == {}
    pool.close()


def test_attach_respects_warm_pool_env(tmp_path, monkeypatch):
    """DCCRG_WARM_POOL=0 keeps the persistent disk cache but never
    starts the background pre-compile sweep."""
    monkeypatch.setenv("DCCRG_WARM_POOL", "0")
    d = str(tmp_path / "cache")
    _seed_manifest(d)
    pool = WarmPool(d)
    assert pool.start_pool is False
    sched = FleetScheduler(str(tmp_path / "ck"), [], warm_pool=pool)
    assert sched.warm is pool and pool._worker is None
    assert warmstart.active() is pool
    pool.close()
    assert warmstart.active() is None


def test_note_incoming_moves_key_to_front(tmp_path):
    """An intake admission's bucket key jumps the prewarm queue —
    the stream knows better than the hit counters."""
    d = str(tmp_path / "cache")
    hot = FleetJob("hot", length=(8, 8, 8), kernel="diffuse")
    cold = FleetJob("cold", length=(4, 4, 4), kernel="diffuse")
    kid_hot, bk_hot = _seed_manifest(d, last_hit=10.0, job=hot)
    kid_cold, _ = _seed_manifest(d, last_hit=99.0, job=cold)
    pool = WarmPool(d, start_pool=False)
    assert pool._queue == [kid_cold, kid_hot]  # recency order
    pool.note_incoming(bk_hot)
    assert pool._queue == [kid_hot, kid_cold]
    pool.close()


# -- SLO projection ---------------------------------------------------

def test_warm_ready_slo_projection(tmp_path):
    """An un-warmed bucket's projected completion is charged its
    measured cold-compile cost up front; once pre-warmed the charge
    drops to zero. A bucket the manifest never measured stays at
    the no-data baseline (never reorders the queue)."""
    d = str(tmp_path / "cache")
    job = _jobs(1)[0]
    _kid, bk = _seed_manifest(d, compile_s=2.5,
                              capacity=8, job=job)
    pool = WarmPool(d, start_pool=False)
    sched = FleetScheduler(str(tmp_path / "ck"), [], warm_pool=pool)
    assert sched.slo.warm_cost.__self__ is pool
    assert not pool.warm_ready(bk)
    assert sched.slo.projected_completion_s(job) == 2.5
    stranger = FleetJob("s", length=(6, 6, 6))
    assert sched.slo.projected_completion_s(stranger) == 0.0
    pool.prewarm(block=True)
    assert pool.errors == []
    assert pool.warm_ready(bk)
    assert sched.slo.projected_completion_s(job) == 0.0
    pool.close()


# -- the fault matrix -------------------------------------------------

def test_every_warm_fault_site_degrades_typed(tmp_path):
    """The full matrix: each WARMSTART_FAULT_SITES damage class
    degrades to cold compile with a typed error and a journaled
    decision — serving still completes with correct digests, no
    crash, no wrong program, no silent warm claim."""
    ref = _digests(_serve(tmp_path, "ck-ref", _jobs()))
    planners = {
        "warm.manifest.write.torn":
            lambda p: p.warm_torn_manifest(),
        "warm.manifest.write.corrupt":
            lambda p: p.warm_corrupt_entry(),
        "warm.manifest.write.stale":
            lambda p: p.warm_stale_epoch(),
        "warm.cache.io": lambda p: p.warm_io_error(op="read"),
    }
    sites = [s for s, _p in faults.WARMSTART_FAULT_SITES]
    assert set(planners) | {"warm.prewarm"} == set(sites)
    for i, (site, make) in enumerate(sorted(planners.items())):
        fleet._FLEET_PROGRAMS.clear()
        telemetry.registry().reset()
        d = str(tmp_path / f"cache{i}")
        ap = Autopilot(quantum=4, clock=lambda: 0.0)
        plan = faults.FaultPlan()
        make(plan)
        with plan:
            # the cold run writes the (damaged) record ...
            pool = WarmPool(d, autopilot=ap, start_pool=False)
            got = _digests(_serve(tmp_path, f"ck-a{i}",
                                  _jobs(), pool))
            assert got == ref, site
            pool.close()
            # ... and the next boot convicts it and falls cold
            fleet._FLEET_PROGRAMS.clear()
            pool2 = WarmPool(d, autopilot=ap, start_pool=False)
            pool2.prewarm(block=True)
            assert pool2._ready == {}, site
            got2 = _digests(_serve(tmp_path, f"ck-b{i}",
                                   _jobs(), pool2))
            assert got2 == ref, site
            pool2.close()
        assert plan.fired(site) >= 1, site
        errs = pool.errors + pool2.errors
        assert errs and all(isinstance(e, WarmCacheError)
                            for _k, e in errs), site
        assert telemetry.registry().counter_total(
            "dccrg_warm_cache_errors_total") >= 1, site
        # no silent warm claim anywhere in the degradation
        decisions = [r["inputs"]["decision"] for r in ap.decisions
                     if r["rule"] == "warmstart.cache"]
        assert "warm" not in decisions, site
        assert {"quarantine", "reject"} & set(decisions), site
        assert replay(list(ap.decisions)) == [], site


def test_death_mid_prewarm_is_typed_and_recoverable(tmp_path):
    """A rank death between two background pre-compiles: blocking
    callers see the typed InjectedRankDeath, the threaded worker
    captures it (never raises into serving), and the cache dir stays
    fully loadable — the next boot simply re-warms."""
    d = str(tmp_path / "cache")
    _seed_manifest(d)
    pool = WarmPool(d, start_pool=False)
    plan = faults.FaultPlan()
    plan.warm_prewarm_death()
    with plan:
        with pytest.raises(faults.InjectedRankDeath):
            pool.prewarm(block=True)
    pool.close()
    # the manifest survived the death untouched
    entries, rejects = warmstart.load_manifest(d)
    assert len(entries) == 1 and rejects == []
    pool2 = WarmPool(d, start_pool=False)
    plan2 = faults.FaultPlan()
    plan2.warm_prewarm_death()
    with plan2:
        worker = pool2.prewarm()
        assert worker.wait(30.0)
    assert isinstance(worker.error, faults.InjectedRankDeath)
    assert telemetry.registry().counter_total(
        "dccrg_prewarm_errors_total") == 1
    # re-warm after the death: everything still works
    pool2._load()
    pool2.prewarm(block=True)
    assert len(pool2._ready) == 1 and pool2.errors == []
    pool2.close()


def test_cache_write_failure_leaves_serving_at_zero_trips(tmp_path):
    """The PR-9 best-effort discipline: every manifest write failing
    (cache dir gone read-only mid-serve) costs warm starts, never
    correctness — the run completes with zero trips and the typed
    errors are recorded, not raised."""
    d = str(tmp_path / "cache")
    pool = WarmPool(d, start_pool=False)
    plan = faults.FaultPlan()
    plan.warm_io_error(times=100, op="write")
    with plan:
        report = _serve(tmp_path, "ck", _jobs(), pool)
    assert all(not r["trips"] for r in report.values())
    assert pool.errors and all(
        isinstance(e, WarmCacheError) for _k, e in pool.errors)
    assert warmstart.load_manifest(d) == ({}, [])  # nothing landed
    pool.close()


# -- journaled decisions ----------------------------------------------

def test_decisions_journal_and_replay(tmp_path):
    """warm/cold decisions land in the autopilot decision file and
    ``replay`` re-derives every one from recorded inputs alone."""
    d = str(tmp_path / "cache")
    journal = tmp_path / "decisions.jsonl"
    ap = Autopilot(quantum=4, clock=lambda: 0.0,
                   decision_file=str(journal))
    pool = WarmPool(d, autopilot=ap, start_pool=False)
    _serve(tmp_path, "ck-a", _jobs(), pool)
    pool.close()
    fleet._FLEET_PROGRAMS.clear()
    pool2 = WarmPool(d, autopilot=ap, start_pool=False)
    pool2.prewarm(block=True)
    _serve(tmp_path, "ck-b", _jobs(), pool2)
    pool2.close()
    kinds = [r["inputs"]["decision"] for r in ap.decisions
             if r["rule"] == "warmstart.cache"]
    assert kinds == ["cold", "warm"]
    assert ap.warm_events == 2
    assert replay(read_journal(str(journal))) == []
    # the rule inventory carries the new rules
    assert "warmstart.cache" in RULES and "warmstart.gc" in RULES


# -- retention GC -----------------------------------------------------

def test_gc_dry_run_default_and_age_bound(tmp_path):
    d = str(tmp_path / "cache")
    kid_old, _ = _seed_manifest(d, last_hit=100.0, job=FleetJob(
        "a", length=(8, 8, 8)))
    kid_new, _ = _seed_manifest(d, last_hit=900.0, job=FleetJob(
        "b", length=(4, 4, 4)))
    report = warmstart.gc(d, max_age_s=300.0, now=1000.0)
    assert report["dry_run"] is True
    assert report["pruned_kids"] == [kid_old]
    assert os.path.exists(warmstart.entry_path(d, kid_old))  # kept
    report = warmstart.gc(d, max_age_s=300.0, now=1000.0,
                          dry_run=False)
    assert report["pruned_kids"] == [kid_old]
    assert not os.path.exists(warmstart.entry_path(d, kid_old))
    assert os.path.exists(warmstart.entry_path(d, kid_new))


def test_gc_size_bound_prunes_least_recently_hit_first(tmp_path):
    d = str(tmp_path / "cache")
    kids = []
    for i, n in enumerate((8, 4, 6)):
        kid, _ = _seed_manifest(d, last_hit=100.0 * (i + 1),
                                job=FleetJob(f"j{n}",
                                             length=(n, n, n)))
        kids.append(kid)
    report = warmstart.gc(d, max_bytes=0, dry_run=False)
    # everything over budget: pruned in last-hit order, oldest first
    assert report["pruned_kids"] == kids
    assert report["bytes_after"] == 0


def test_gc_never_prunes_inflight_prewarm(tmp_path):
    d = str(tmp_path / "cache")
    kid, _ = _seed_manifest(d, last_hit=0.0)
    pool = WarmPool(d, start_pool=False)
    pool._inflight.add(kid)
    report = pool.gc(max_age_s=1.0, dry_run=False)
    assert report["pruned_kids"] == []
    assert os.path.exists(warmstart.entry_path(d, kid))
    pool._inflight.discard(kid)
    pool._queue = []
    report = pool.gc(max_age_s=1.0, dry_run=False)
    assert report["pruned_kids"] == [kid]
    pool.close()


def test_gc_sweeps_dead_pid_temp_litter(tmp_path):
    d = str(tmp_path / "cache")
    warmstart.ensure_cache(d)
    mdir = os.path.join(d, "manifest")
    dead = os.path.join(mdir, ".x.rec.tmp.999999999")
    live = os.path.join(mdir, f".y.rec.tmp.{os.getpid()}")
    for p in (dead, live):
        with open(p, "w") as f:
            f.write("partial")
    assert warmstart.stale_temp_files(d) == [dead]
    report = warmstart.gc(d, dry_run=False)
    assert report["swept_tmp"] == [dead]
    assert not os.path.exists(dead)
    assert os.path.exists(live)  # the writer is still alive


def test_gc_applied_prunes_are_journaled(tmp_path):
    d = str(tmp_path / "cache")
    _seed_manifest(d, last_hit=0.0)
    ap = Autopilot(quantum=4, clock=lambda: 0.0)
    pool = WarmPool(d, autopilot=ap, start_pool=False)
    pool._queue = []
    pool.gc(max_age_s=1.0, dry_run=False)
    recs = [r for r in ap.decisions if r["rule"] == "warmstart.gc"]
    assert len(recs) == 1 and recs[0]["inputs"]["n"] >= 1
    assert replay(list(ap.decisions)) == []
    assert pool.entries == {}
    pool.close()


def test_gc_io_error_degrades_to_null_report(tmp_path):
    d = str(tmp_path / "cache")
    kid, _ = _seed_manifest(d)
    plan = faults.FaultPlan()
    plan.warm_io_error(op="gc")
    with plan:
        report = warmstart.gc(d, max_age_s=0.0, dry_run=False)
    assert "error" in report and report["pruned"] == []
    assert os.path.exists(warmstart.entry_path(d, kid))


# -- CLI --------------------------------------------------------------

def test_cli_list_and_gc_smoke(tmp_path, capsys):
    d = str(tmp_path / "cache")
    _seed_manifest(d)
    assert warmstart._main(["list", "--dir", d]) == 0
    out = capsys.readouterr().out
    assert "1 entries" in out and warmstart.cache_epoch() in out
    assert warmstart._main(["gc", "--dir", d, "--max-age-s", "1",
                            ]) == 0
    out = capsys.readouterr().out
    assert "would prune" in out  # dry-run default
    entries, _ = warmstart.load_manifest(d)
    assert len(entries) == 1  # nothing actually pruned
    assert warmstart._main(["gc", "--dir", d, "--max-age-s", "1",
                            "--apply"]) == 0
    entries, _ = warmstart.load_manifest(d)
    assert entries == {}
    assert warmstart._main(["list"]) == 2  # no dir anywhere


# -- AOT fallback -----------------------------------------------------

def test_aot_fallback_on_aval_mismatch():
    """The served AOT executable falls back to the jit path on an
    input mismatch (counted, never raised); execution errors pass
    through untouched."""
    calls = []

    class Compiled:
        def __call__(self, x):
            calls.append("aot")
            if x != 1:
                raise TypeError("aval mismatch")
            return "aot-ok"

    def jitted(x):
        calls.append("jit")
        return "jit-ok"

    fn = warmstart._with_fallback(Compiled(), jitted)
    assert fn(1) == "aot-ok"
    assert fn(2) == "jit-ok"
    assert calls == ["aot", "aot", "jit"]
    assert telemetry.registry().counter_total(
        "dccrg_warm_misses_total", where="aot_fallback") == 1

    class Exploding:
        def __call__(self, x):
            raise RuntimeError("RESOURCE_EXHAUSTED: oom")

    fn = warmstart._with_fallback(Exploding(), jitted)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        fn(1)
