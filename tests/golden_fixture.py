"""Builder for the golden .dc fixture (tests/data/golden.dc).

The fixture pins the on-disk checkpoint format: the test re-saves the
loaded grid and asserts byte identity, so ANY change to the .dc layout
(metadata records, offset table, payload interleaving, variable-field
encoding) fails loudly instead of silently breaking old checkpoints.

Regenerate (only on a DELIBERATE format change) with:
    python tests/golden_fixture.py
"""

import numpy as np
import jax.numpy as jnp

GOLDEN_SCHEMA = {
    "density": jnp.float32,
    "flag": jnp.int32,
    "count": jnp.int32,
    "pos": ((4, 3), jnp.float32),  # variable, truncated by "count"
}
GOLDEN_VARIABLE = {"pos": "count"}


def build_golden_grid(mesh=None):
    """Deterministic small AMR grid: (4, 4, 2) level-0, two refined
    cells, partition-independent per-cell values derived from ids."""
    from dccrg_tpu.grid import Grid

    g = (Grid(cell_data=GOLDEN_SCHEMA)
         .set_initial_length((4, 4, 2))
         .set_periodic(True, False, False)
         .set_maximum_refinement_level(1)
         .set_neighborhood_length(1)
         .set_geometry("cartesian", start=(0.0, 0.0, 0.0),
                       level_0_cell_length=(0.25, 0.25, 0.5))
         .initialize(mesh))
    g.refine_completely(np.uint64(1))
    g.refine_completely(np.uint64(22))
    g.stop_refining()
    cells = g.plan.cells
    ids = cells.astype(np.float64)
    g.set_many(cells, {
        "density": (ids * 0.5).astype(np.float32),
        "flag": (cells % np.uint64(7)).astype(np.int32),
        "count": (cells % np.uint64(5)).astype(np.int32),
    })
    pos = np.zeros((len(cells), 4, 3), dtype=np.float32)
    for r in range(4):
        for c in range(3):
            pos[:, r, c] = (ids * (r + 1) + c).astype(np.float32)
    g.set("pos", cells, pos)
    return g


if __name__ == "__main__":
    import os, sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    g = build_golden_grid()
    out = os.path.join(os.path.dirname(__file__), "data", "golden.dc")
    g.save_grid_data(out, header=b"golden-v1\n", variable=GOLDEN_VARIABLE)
    print(f"wrote {out} ({os.path.getsize(out)} bytes, "
          f"{len(g.plan.cells)} cells)")
