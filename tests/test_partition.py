"""Partitioner tests (the Zoltan replacement, dccrg.hpp:8482-8720)."""

import numpy as np
import pytest

from dccrg_tpu import Mapping

from dccrg_tpu.partition import hilbert_key, morton_key, partition_cells


def test_morton_keys_unique_and_local():
    m = Mapping((4, 4, 4))
    cells = np.arange(1, 65, dtype=np.uint64)
    keys = morton_key(m, cells)
    assert len(np.unique(keys)) == 64
    # morton of (0,0,0) is 0; of (1,0,0) is 1; of (0,1,0) is 2
    assert keys[0] == 0 and keys[1] == 1 and keys[4] == 2


def test_hilbert_keys_are_a_permutation_with_unit_steps():
    m = Mapping((4, 4, 4))
    cells = np.arange(1, 65, dtype=np.uint64)
    keys = hilbert_key(m, cells)
    assert len(np.unique(keys)) == 64
    assert keys.min() == 0 and keys.max() == 63
    # the defining Hilbert property: consecutive keys are adjacent cells
    order = np.argsort(keys)
    idx = m.get_indices(cells[order]).astype(np.int64)
    steps = np.abs(np.diff(idx, axis=0)).sum(axis=1)
    np.testing.assert_array_equal(steps, np.ones(63))


def test_block_partition_contiguous_and_balanced():
    m = Mapping((8, 1, 1))
    cells = np.arange(1, 9, dtype=np.uint64)
    owner = partition_cells(m, cells, 4, "block")
    np.testing.assert_array_equal(owner, [0, 0, 1, 1, 2, 2, 3, 3])


def test_weighted_partition():
    m = Mapping((4, 1, 1))
    cells = np.arange(1, 5, dtype=np.uint64)
    # one heavy cell gets its own device
    owner = partition_cells(m, cells, 2, "block", weights=np.array([3.0, 1.0, 1.0, 1.0]))
    assert owner[0] == 0
    assert np.all(owner[1:] == 1)


def test_pins_override():
    m = Mapping((8, 1, 1))
    cells = np.arange(1, 9, dtype=np.uint64)
    owner = partition_cells(m, cells, 4, "block", pins={1: 3, 8: 0})
    assert owner[0] == 3 and owner[7] == 0
    with pytest.raises(ValueError):
        partition_cells(m, cells, 4, "block", pins={1: 9})


def test_partition_balance_on_refined_levels():
    m = Mapping((2, 2, 2), maximum_refinement_level=1)
    kids = m.get_all_children(np.uint64(1))
    cells = np.sort(np.concatenate([np.arange(2, 9, dtype=np.uint64), kids]))
    for method in ("block", "morton", "hilbert"):
        owner = partition_cells(m, cells, 5, method)
        counts = np.bincount(owner, minlength=5)
        assert counts.max() - counts.min() <= 1, method


def test_rcb_partition_balanced_and_compact():
    """RCB (Zoltan's geometric default): near-equal part weights and
    compact boxes — the cut surface must beat a block split."""
    from dccrg_tpu.partition import partition_cells
    from dccrg_tpu.mapping import Mapping

    mp = Mapping((16, 16, 16))
    cells = np.arange(1, 16**3 + 1, dtype=np.uint64)
    owner = partition_cells(mp, cells, 8, "rcb")
    counts = np.bincount(owner, minlength=8)
    assert counts.min() >= 16**3 // 8 - 64 and counts.max() <= 16**3 // 8 + 64
    # compactness: count faces crossing parts along x/y/z
    def cut_faces(own3):
        c = 0
        for d in range(3):
            a = np.swapaxes(own3, 0, d)
            c += int((a[1:] != a[:-1]).sum())
        return c
    own3 = owner.reshape(16, 16, 16)  # z, y, x
    block3 = partition_cells(mp, cells, 8, "block").reshape(16, 16, 16)
    assert cut_faces(own3) <= cut_faces(block3)
    # rcb boxes for 8 parts on a cube should be the 2x2x2 octants:
    # surface = 3 internal planes = 3 * 16^2 faces
    assert cut_faces(own3) == 3 * 16 * 16


def test_rcb_respects_weights_and_pins():
    from dccrg_tpu.partition import partition_cells
    from dccrg_tpu.mapping import Mapping

    mp = Mapping((8, 8, 1))
    cells = np.arange(1, 65, dtype=np.uint64)
    w = np.ones(64)
    w[:8] = 50.0  # first x-row dominates
    owner = partition_cells(mp, cells, 2, "rcb", weights=w, pins={64: 0})
    loads = np.bincount(owner, weights=w, minlength=2)
    assert abs(loads[0] - loads[1]) / loads.sum() < 0.2
    assert owner[63] == 0  # pinned


def test_rcb_on_refined_grid():
    from dccrg_tpu.grid import Grid
    import jax, jax.numpy as jnp
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), ("dev",))
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((6, 6, 2))
         .set_maximum_refinement_level(1)
         .set_load_balancing_method("rcb")
         .initialize(mesh))
    for c in (1, 2, 7):
        g.refine_completely(c)
    g.stop_refining()
    g.balance_load()
    counts = np.bincount(g.plan.owner, minlength=4)
    assert counts.min() > 0
    g.update_copies_of_remote_neighbors()


def test_single_part_still_validates_weights():
    """n_parts==1 takes an early return but bad weights must still
    raise (advisor round 3)."""
    mp = Mapping((4, 4, 1))
    cells = np.arange(1, 17, dtype=np.uint64)
    with pytest.raises(ValueError, match=">= 0"):
        partition_cells(mp, cells, 1, weights=-np.ones(16))
    with pytest.raises(ValueError, match="shape"):
        partition_cells(mp, cells, 1, weights=np.ones(3))


def test_cut_without_edges_is_rcb():
    mp = Mapping((8, 8, 1))
    cells = np.arange(1, 65, dtype=np.uint64)
    a = partition_cells(mp, cells, 4, method="cut")
    b = partition_cells(mp, cells, 4, method="rcb")
    np.testing.assert_array_equal(a, b)


def test_swap_pass_heals_boundary_the_greedy_cannot():
    """Two wrong-side cells straddling the interface: each single move
    is blocked by the balance caps (it would overload one part), but
    the KL-style pair swap is balance-neutral and heals both — the
    tail Zoltan PHG's refinement covers beyond the greedy sweep."""
    from dccrg_tpu.partition import refine_cut

    owner = np.array([0, 0, 0, 1, 0, 1, 1, 1], dtype=np.int32)
    n = len(owner)
    src = np.concatenate([np.arange(n - 1), np.arange(1, n)])
    dst = np.concatenate([np.arange(1, n), np.arange(n - 1)])

    def cut(o):
        return int(np.sum(o[src] != o[dst]))

    assert cut(owner) == 6
    out = refine_cut(owner, np.ones(n), src, dst, 2, tol=1.1)
    assert cut(out) == 2, out  # clean split
    np.testing.assert_array_equal(np.bincount(out), [4, 4])


def test_refine_cut_reduces_edge_cut_within_balance():
    """A jagged 1-D chain partition: refinement should heal boundary
    cells surrounded by the other device without wrecking balance."""
    from dccrg_tpu.partition import refine_cut

    n = 64
    owner = np.zeros(n, dtype=np.int32)
    owner[n // 2:] = 1
    # isolated wrong-side islands (the jagged-boundary case the greedy
    # majority sweep exists to heal)
    owner[20] = 1
    owner[44] = 0
    src = np.concatenate([np.arange(n - 1), np.arange(1, n)])
    dst = np.concatenate([np.arange(1, n), np.arange(n - 1)])
    w = np.ones(n)

    def cut(o):
        return int(np.sum(o[src] != o[dst]))

    before = cut(owner)
    out = refine_cut(owner, w, src, dst, 2)
    assert cut(out) < before
    loads = np.bincount(out, minlength=2)
    assert loads.max() <= 1.1 * n / 2 + 1
    assert loads.min() >= 0.9 * n / 2 - 1
