"""Fleet execution layer: batched many-grid multiplexing with
per-job isolation.

The acceptance pins: with >= 32 concurrent jobs in ONE batch, an
injected NaN trip (and separately an injected OOM) in one job rolls
back / requeues ONLY that job — every other job's final field bytes
are identical to a run without the fault, and every job's fleet-run
digest (the victim included, after rollback + clean replay) matches
its solo one-grid-at-a-time ``Grid.run_steps`` digest bitwise. Plus:
per-slot checkpoint round-trips that resume into a DIFFERENT bucket
position, drain/backfill past bucket capacity, compile sharing across
same-shape jobs, per-stem delta chains + retention GC, preemption =
emergency save + requeue + bitwise resume, and the CLI."""

import glob
import json
import os

import pytest

import jax.numpy as jnp

from dccrg_tpu import checkpoint as checkpoint_mod
from dccrg_tpu import faults, resilience, supervise
from dccrg_tpu.faults import FaultPlan
from dccrg_tpu.fleet import (FLEET_KERNELS, FleetJob, GridBatch,
                             _FLEET_PROGRAMS, run_solo, template_grid)
from dccrg_tpu.fuzz import fleet_isolation_case
from dccrg_tpu.scheduler import FleetPreemptedError, FleetScheduler

pytestmark = pytest.mark.fleet

N_BIG = 33  # the >= 32-concurrent-jobs acceptance fleet


def _specs(count=N_BIG, steps=14, kernel="diffuse", **kw):
    """Fresh job objects (the scheduler mutates runtime state, so
    every run gets its own)."""
    return [FleetJob(f"j{i:03d}", length=(8, 8, 8), kernel=kernel,
                     n_steps=steps, params=(0.02 + 0.005 * (i % 5),),
                     seed=i, checkpoint_every=5, **kw)
            for i in range(count)]


def _solo_digests(specs):
    """Solo ``Grid.run_steps`` digests, ONE shared grid + compile for
    every job of a bucket (re-initialized per job — byte-identical to
    a fresh grid, cheaper than 33 compiles)."""
    grids = {}
    out = {}
    for j in specs:
        g = grids.get(j.bucket_key())
        if g is None:
            g = grids[j.bucket_key()] = template_grid(j)
        j.apply_init(g)
        if j.n_steps:
            g.run_steps(j.resolved_kernel(), j.fields_in, j.fields_out,
                        j.n_steps,
                        extra_args=tuple(jnp.float32(p)
                                         for p in j.params))
        out[j.name] = checkpoint_mod.state_digest(g)
    return out


@pytest.fixture(scope="module")
def big_solo():
    return _solo_digests(_specs())


@pytest.fixture(scope="module")
def big_nofault(tmp_path_factory, big_solo):
    """The no-fault fleet reference run — also pins the base parity:
    every fleet digest equals its solo digest bitwise."""
    wd = tmp_path_factory.mktemp("fleet_ref")
    sched = FleetScheduler(wd, _specs(), quantum=4)
    report = sched.run()
    assert all(r["status"] == "done" for r in report.values())
    assert {n: r["digest"] for n, r in report.items()} == big_solo
    # all 33 jobs really were CONCURRENT: one bucket instance, every
    # job admitted into it
    insts = [b for bs in sched.buckets.values() for b in bs]
    assert len(insts) == 1 and insts[0].capacity >= N_BIG
    return {n: r["digest"] for n, r in report.items()}


def test_fleet_parity_solo_bitwise(big_nofault, big_solo):
    assert big_nofault == big_solo


def test_nan_trip_isolates_one_job(tmp_path, big_solo, big_nofault):
    """The acceptance pin: one poisoned slot in a >= 32-job batch
    trips, rolls back from its OWN checkpoint and replays clean;
    every neighbor's final bytes equal the fault-free run."""
    victim = "j017"
    plan = FaultPlan(seed=1)
    plan.nan_poison("rho", step=9, job=victim)
    with plan:
        report = FleetScheduler(tmp_path, _specs(), quantum=4).run()
    assert plan.fired("step.poison") == 1
    assert all(r["status"] == "done" for r in report.values())
    # only the victim tripped, exactly once
    assert {n for n, r in report.items() if r["trips"]} == {victim}
    # neighbors: bitwise identical to the run WITHOUT the fault
    for n, r in report.items():
        if n != victim:
            assert r["digest"] == big_nofault[n], n
    # and the victim reconverged to its solo digest (rollback + clean
    # replay — the poison rule was consumed)
    assert report[victim]["digest"] == big_solo[victim]


@pytest.mark.sdc
def test_silent_flip_isolates_one_job(tmp_path, big_solo, big_nofault):
    """The SDC acceptance pin: a FINITE bit-flip in one slot of the
    >= 32-job batch — invisible to the finiteness watchdog by
    construction — is convicted by the in-program integrity
    invariants within one quantum, ONLY the victim trips/rolls
    back/replays, and every other job's final bytes equal both the
    no-fault fleet run and its solo digest bitwise."""
    victim = "j011"
    plan = FaultPlan(seed=4)
    plan.silent_flip("rho", step=9, job=victim)
    with plan:
        sched = FleetScheduler(tmp_path, _specs(), quantum=4)
        report = sched.run()
    assert plan.fired("step.flip") == 1
    assert all(r["status"] == "done" for r in report.values())
    # only the victim was convicted, exactly once, as CORRUPT
    assert {n for n, r in report.items() if r["trips"]} == {victim}
    assert report[victim]["sdc_trips"] == 1
    assert sched.suspects[0] == 1
    for n, r in report.items():
        if n != victim:
            assert r["digest"] == big_nofault[n], n
            assert r["digest"] == big_solo[n], n
    # the victim rolled back and replayed clean
    assert report[victim]["digest"] == big_solo[victim]


def test_oom_isolates_one_job(tmp_path, big_solo, big_nofault):
    """Separately: a job-scoped injected RESOURCE_EXHAUSTED requeues
    only that job (it re-admits from its own checkpoint stem);
    neighbors' bytes never move."""
    victim = "j005"
    plan = FaultPlan(seed=2)
    plan.resource_exhausted(job=victim)
    with plan:
        report = FleetScheduler(tmp_path, _specs(), quantum=4).run()
    assert plan.fired("step.dispatch") == 1
    assert all(r["status"] == "done" for r in report.values())
    assert report[victim]["requeues"] == 1
    assert {n for n, r in report.items() if r["trips"]} == {victim}
    for n, r in report.items():
        if n != victim:
            assert r["digest"] == big_nofault[n], n
    assert report[victim]["digest"] == big_solo[victim]


def test_real_batch_oom_shrinks_the_bucket(tmp_path, monkeypatch):
    """A REAL (unattributed) RESOURCE_EXHAUSTED from the batched
    dispatch must SHRINK the bucket, not just requeue: freed slots
    are backfilled on the next tick and occupancy alone frees no
    device memory (state arrays + program are sized by capacity), so
    without a capacity rebuild the same OOM would repeat forever.
    Survivors migrate bit-exactly, the requeued half re-admits from
    its keyframes, and every digest still matches solo."""
    solo = _solo_digests(_specs(count=8, steps=10))
    real_step = GridBatch.step

    def step(self, budget):
        if self.capacity > 4:
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: out of memory (injected)")
        return real_step(self, budget)

    monkeypatch.setattr(GridBatch, "step", step)
    sched = FleetScheduler(tmp_path, _specs(count=8, steps=10),
                           quantum=4)
    report = sched.run()
    assert all(r["status"] == "done" for r in report.values())
    assert {n: r["digest"] for n, r in report.items()} == solo
    assert any(r["requeues"] for r in report.values())
    insts = [b for bs in sched.buckets.values() for b in bs]
    assert len(insts) == 1 and insts[0].capacity <= 4


def test_no_resume_purges_stale_stems(tmp_path):
    """``resume=False`` is a from-scratch contract: a workdir holding
    a previous run's stems is purged at admission — otherwise the
    first trip/requeue would ``_load_newest`` the stale higher-step
    state (and the per-save GC would keep those stale files over this
    run's fresh step-0 keyframe)."""
    FleetScheduler(tmp_path, _specs(count=2, steps=8), quantum=4).run()
    assert glob.glob(os.path.join(str(tmp_path), "j000_*"))
    solo = _solo_digests(_specs(count=2, steps=8))
    # rerun no-resume with a NaN trip: rollback must land on THIS
    # run's step-0 keyframe, not the old run's final state
    plan = FaultPlan(seed=7)
    plan.nan_poison("rho", step=5, job="j000")
    with plan:
        report = FleetScheduler(tmp_path, _specs(count=2, steps=8),
                                quantum=4, resume=False).run()
    assert all(r["status"] == "done" for r in report.values())
    assert report["j000"]["trips"] == 1
    assert {n: r["digest"] for n, r in report.items()} == solo


def test_batch_oom_with_one_job_surfaces(tmp_path, monkeypatch):
    """Halving converges: when even a one-job bucket still OOMs, the
    failure surfaces as ResilienceExhaustedError instead of looping."""
    monkeypatch.setattr(
        GridBatch, "step",
        lambda self, budget: (_ for _ in ()).throw(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory")))
    sched = FleetScheduler(tmp_path, _specs(count=4, steps=6),
                           quantum=4)
    with pytest.raises(resilience.ResilienceExhaustedError):
        sched.run()


def test_per_slot_roundtrip_resumes_into_different_slot(tmp_path):
    """Save a job from a LIVE batch, kill the fleet, resume into a
    different bucket position — final digest bit-identical to an
    uninterrupted solo run."""
    mk = lambda prios: [  # noqa: E731
        FleetJob(n, length=(8, 8, 8), n_steps=20, params=(0.03,),
                 seed=i, checkpoint_every=4, priority=p)
        for i, (n, p) in enumerate(zip("abcd", prios))]
    solo = _solo_digests(mk((0, 0, 0, 0)))
    sched = FleetScheduler(tmp_path, mk((0, 0, 0, 0)), quantum=4)
    sched.run(max_ticks=2)  # mid-run: per-job checkpoints exist
    slots1 = {j.name: s for _b, s, j in sched.active_jobs()}
    assert slots1 == {"a": 0, "b": 1, "c": 2, "d": 3}
    del sched  # the 'kill': live batch state is abandoned

    # resume with REVERSED admission priorities: every job restores
    # from its own stem into a different slot
    sched2 = FleetScheduler(tmp_path, mk((0, 1, 2, 3)), quantum=4)
    sched2._admit_pending()
    slots2 = {j.name: s for _b, s, j in sched2.active_jobs()}
    assert slots2 == {"d": 0, "c": 1, "b": 2, "a": 3}
    resumed = {j.name: j.steps_done for _b, _s, j in sched2.active_jobs()}
    assert all(0 < v < 20 for v in resumed.values()), resumed
    report = sched2.run()
    assert {n: r["digest"] for n, r in report.items()} == solo


def test_backfill_drains_past_capacity(tmp_path):
    """More jobs than slots: finishing jobs free slots the queue
    backfills; every job completes with its solo digest."""
    specs = _specs(count=10, steps=8)
    solo = _solo_digests(_specs(count=10, steps=8))
    sched = FleetScheduler(tmp_path, specs, max_batch=4, quantum=3)
    report = sched.run()
    assert {n: r["digest"] for n, r in report.items()} == solo
    insts = [b for bs in sched.buckets.values() for b in bs]
    assert len(insts) == 1 and insts[0].capacity == 4


def test_same_shape_jobs_share_one_program(tmp_path):
    """Two batches with the same bucket key (a drained + recreated
    bucket) reuse ONE compiled program pair."""
    proto = FleetJob("p", length=(8, 8, 8), params=(0.1,))
    b1 = GridBatch(proto, 16)
    b1._programs()
    n_before = len(_FLEET_PROGRAMS)
    b2 = GridBatch(FleetJob("q", length=(8, 8, 8), params=(0.2,)), 16)
    b2._programs()
    assert len(_FLEET_PROGRAMS) == n_before
    # a different shape is a different bucket -> its own program
    b3 = GridBatch(FleetJob("r", length=(4, 4, 4), params=(0.2,)), 16)
    b3._programs()
    assert len(_FLEET_PROGRAMS) == n_before + 1


def test_batch_digest_matches_state_digest():
    """GridBatch.digest over a slot equals checkpoint.state_digest of
    a grid holding the same bytes — the bridge every bitwise assertion
    in this file crosses."""
    job = FleetJob("d", length=(6, 6, 6), seed=9)
    batch = GridBatch(job, 4)
    job.apply_init(batch.grid)
    g_digest = checkpoint_mod.state_digest(batch.grid)
    slot = batch.admit(job, from_grid=True)
    assert batch.digest(slot) == g_digest


def test_job_scoped_rules_do_not_leak():
    """A job= rule never fires for another job, nor at the plain
    per-grid poison site."""
    plan = FaultPlan(seed=0)
    plan.nan_poison("rho", step=3, job="right")
    plan.resource_exhausted(job="right")
    with plan:
        # plain grid-site poison carries no job -> no match
        g = template_grid(FleetJob("x", length=(4, 4, 4)))
        assert faults.poison_step(g, 3) == []
        # wrong job -> no match; right job -> fires
        assert faults.poison_fleet("wrong", 0, 10) == []
        hits = faults.poison_fleet("right", 0, 10)
        assert [(h[0], h[3]) for h in hits] == [("rho", 3)]
        faults.fire("step.dispatch", mode="fleet", job="wrong", step=0)
        with pytest.raises(faults.SimulatedResourceExhausted):
            faults.fire("step.dispatch", mode="fleet", job="right",
                        step=0)


def test_transient_dispatch_error_retries_in_place(tmp_path):
    """An UNAVAILABLE-class dispatch error for one job retries with
    backoff — no trip, no rollback, bitwise solo parity."""
    specs = _specs(count=4, steps=10)
    solo = _solo_digests(_specs(count=4, steps=10))
    plan = FaultPlan(seed=3)
    plan.dispatch_error(job="j002")
    with plan:
        report = FleetScheduler(tmp_path, specs, quantum=4).run()
    assert plan.fired("supervise.dispatch") == 1
    assert report["j002"]["transient_retries"] == 1
    assert all(r["trips"] == 0 for r in report.values())
    assert {n: r["digest"] for n, r in report.items()} == solo


def test_unrecoverable_nan_fails_only_that_job(tmp_path):
    """A poison that re-lands on every replay exhausts the victim's
    bounded retries -> FAILED; every other job still finishes with
    its solo digest."""
    specs = _specs(count=6, steps=12)
    for j in specs:
        j.max_retries = 2
    solo = _solo_digests(_specs(count=6, steps=12))
    plan = FaultPlan(seed=4)
    plan.nan_poison("rho", step=7, job="j001", times=faults.EVERY)
    with plan:
        report = FleetScheduler(tmp_path, specs, quantum=4).run()
    assert report["j001"]["status"] == "failed"
    assert report["j001"]["trips"] == 3  # initial + 2 bounded retries
    for n, r in report.items():
        if n != "j001":
            assert r["status"] == "done" and r["digest"] == solo[n]


def test_preempt_emergency_saves_and_resumes_bitwise(tmp_path):
    """A preemption signal at a quantum boundary: every admitted job
    emergency-checkpoints into its own stem, the fleet exits with the
    resumable code 75, and a rerun over the same directory finishes
    every job bitwise equal to an uninterrupted fleet."""
    solo = _solo_digests(_specs(count=6, steps=16))
    plan = FaultPlan(seed=5)
    plan.preempt_signal(step=1)  # the second scheduler tick
    sched = FleetScheduler(tmp_path, _specs(count=6, steps=16),
                           quantum=3)
    with plan:
        with pytest.raises(FleetPreemptedError) as ei:
            sched.run()
    assert ei.value.exit_code == supervise.RESUMABLE_EXIT == 75
    assert len(ei.value.requeued) == 6
    # every stem has a verifying emergency checkpoint
    for i in range(6):
        entries = supervise.list_checkpoints(tmp_path, f"j{i:03d}")
        assert entries
        resilience.verify_chain(entries[0][1])
    report = FleetScheduler(tmp_path, _specs(count=6, steps=16),
                            quantum=3).run()
    assert {n: r["digest"] for n, r in report.items()} == solo


def test_delta_chains_and_retention_per_stem(tmp_path):
    """Multi-field jobs save dirty-field DELTAS per stem (the step
    dirties only rho; aux is static), chains verify end to end, and
    per-stem retention GC leaves whole chains only."""
    specs = [FleetJob(f"m{i}", length=(6, 6, 6), n_steps=30,
                      params=(0.02,), seed=i, checkpoint_every=3,
                      cell_data={"rho": jnp.float32,
                                 "aux": ((4,), jnp.int32)})
             for i in range(3)]
    report = FleetScheduler(tmp_path, specs, quantum=3,
                            keep_last=2).run()
    assert all(r["status"] == "done" for r in report.values())
    assert glob.glob(os.path.join(tmp_path, "m0_*.dcd")), \
        "no delta saves landed"
    for i in range(3):
        chains = supervise.chain_report(tmp_path, stem=f"m{i}")
        assert chains
        for _stem, links in chains:
            assert all(status == "OK" for _s, _p, _k, status in links)
        # retention ran per stem: far fewer steps kept than the ~10
        # periodic saves each job made
        steps = {s for s, _p in supervise.list_checkpoints(
            tmp_path, f"m{i}")}
        assert len(steps) <= 4


def test_fleet_fuzz_isolation_scenario():
    """The fuzz-oracle wiring: seeded randomized fleets with one
    poisoned slot; every job must match its solo digest and only the
    victim may trip (fuzz.fleet_isolation_case)."""
    for seed in (0, 1):
        out = fleet_isolation_case(seed)
        assert out["trips"] >= 1


@pytest.mark.fuzz
def test_fleet_fuzz_more_seeds():
    for seed in (2, 3):
        fleet_isolation_case(seed)


def test_cli_runs_a_job_file(tmp_path, capsys):
    """python -m dccrg_tpu.fleet smoke: a job file runs to completion
    and reports one JSON row per job plus a summary."""
    from dccrg_tpu.fleet import _main

    spec = {"jobs": [
        {"name": "a", "n": 6, "kernel": "diffuse", "steps": 6,
         "dt": 0.05, "seed": 1},
        {"name": "b", "n": 6, "kernel": "advect_x", "steps": 8,
         "params": [0.4], "priority": 2},
    ]}
    jf = tmp_path / "jobs.json"
    jf.write_text(json.dumps(spec))
    rc = _main([str(jf), "--workdir", str(tmp_path / "wd"),
                "--quantum", "3"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    rows = [json.loads(line) for line in out]
    byname = {r["name"]: r for r in rows if "name" in r}
    assert byname["a"]["status"] == "done" and byname["a"]["steps"] == 6
    assert byname["b"]["status"] == "done" and byname["b"]["steps"] == 8
    summary = rows[-1]["summary"]
    assert summary["jobs"] == 2 and summary["done"] == 2


def test_registry_and_demo_cli(tmp_path, capsys):
    assert {"diffuse", "advect_x"} <= set(FLEET_KERNELS)
    from dccrg_tpu.fleet import _main

    rc = _main(["--demo", "3", "--n", "6", "--steps", "5",
                "--workdir", str(tmp_path)])
    assert rc == 0
    rows = [json.loads(x) for x in
            capsys.readouterr().out.strip().splitlines()]
    assert rows[-1]["summary"]["done"] == 3


def test_nan_confined_mid_run_not_just_at_the_end():
    """Stronger than final digests: with NaN RESIDENT in one slot
    while the batch steps, the neighbor slots' bytes match a batch
    that never saw the NaN — the vmapped program has no cross-batch
    ops and per-slot selects preserve bits exactly."""
    import numpy as np

    def mk_batch():
        b = GridBatch(FleetJob("p", length=(6, 6, 6), params=(0.05,)),
                      4)
        for slot, seed in enumerate((10, 11, 12)):
            j = FleetJob(f"s{slot}", length=(6, 6, 6), params=(0.05,),
                         seed=seed)
            j.apply_init(b.grid)
            b.admit(j, from_grid=True)
        return b

    poisoned, clean = mk_batch(), mk_batch()
    poisoned.poison(1, "rho", [5], float("nan"))
    budget = np.array([3, 3, 3, 0], np.int32)
    poisoned.step(budget)
    clean.step(budget)
    ok = poisoned.finite_slots()
    assert list(ok[:3]) == [True, False, True]
    assert poisoned.digest(0) == clean.digest(0)
    assert poisoned.digest(2) == clean.digest(2)
    assert poisoned.digest(1) != clean.digest(1)


def test_run_solo_matches_batch_of_one(tmp_path):
    """run_solo (the Grid.run_steps baseline) == a fleet of ONE job:
    the batch axis itself never perturbs a job's bytes."""
    job = FleetJob("one", length=(8, 8, 8), n_steps=9, params=(0.07,),
                   seed=42, kernel="advect_x")
    solo = run_solo(FleetJob("one", length=(8, 8, 8), n_steps=9,
                             params=(0.07,), seed=42,
                             kernel="advect_x"))
    report = FleetScheduler(tmp_path, [job], quantum=4).run()
    assert report["one"]["digest"] == solo
