"""Model-zoo throughput + the ghost-split outer-re-pass reduction.

Two leg families, JSON rows to stdout like the other bench emitters:

- **model legs** — cell-updates/s per zoo model (advection / MHD /
  Vlasov) through the fused ``Grid.run_steps`` loop on one device:
  trend keys ``advect<n>_updates_per_sec`` /
  ``mhd<n>_updates_per_sec`` / ``vlasov<n>_updates_per_sec``
  (``bench/trend.py`` tracks ``*updates_per_sec`` higher-is-better
  unchanged). The MHD number counts cell-updates across BOTH
  operator-split passes; the Vlasov row also reports
  ``phase_updates_per_sec`` (cells x Nv — the wide payload's true
  element throughput).

- **ghost-split leg** (``--split``, needs the multi-device mesh this
  file self-configures) — the per-field ghost-split overlap
  (``DCCRG_GHOST_SPLIT``) vs the full outer re-pass on the
  multi-device MHD model: emits ``outer_repass_rows_full`` /
  ``outer_repass_rows_split`` (outer row-slots recomputed per
  super-step, the reduction the split buys) plus the directional
  trend key ``ghost_split_rows_vs_baseline`` (full/split ratio,
  higher is better), and ASSERTS the two programs' final states are
  BITWISE identical per leg — the bench doubles as the parity check.

Every leg follows the null-on-failure discipline: a failed leg emits
``null`` metrics and the bench exits 0 (never a fabricated number);
the device probe is the hang-proof ``resilience.safe_devices`` one.

Run:  timeout -k 10 900 python bench/models_bench.py [--n 16]
      [--steps 40] [--no-split]

(``timeout -k`` so a wedged backend can never hang CI; 900 s covers
the CPU host with margin.)
"""

import argparse
import json
import os
import sys
import time
import traceback
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# the ghost-split leg needs a multi-device mesh: force the virtual
# CPU mesh BEFORE jax loads (the conftest discipline)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def emit(row):
    print(json.dumps(row), flush=True)


def probe():
    from dccrg_tpu.resilience import safe_devices

    return safe_devices(timeout=120)


def _bench_loop(run_fn, steps, reps=3):
    """Best-of-reps wall for ``run_fn(steps)`` (first call compiles
    outside the window)."""
    run_fn(1)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        run_fn(steps)
        best = min(best, time.perf_counter() - t0)
    return best


def model_leg(name, n, steps):
    from dccrg_tpu.models import GridAdvection, GridMHD, GridVlasov

    row = {"leg": name, "n": n, "steps": steps}
    try:
        if name == "advect":
            m = GridAdvection(n=n, nz=n)
            dt = 0.4 * m.max_time_step()
            wall = _bench_loop(lambda s: m.run(s, dt=dt), steps)
            per_pass = 1
        elif name == "mhd":
            m = GridMHD(n=n)
            dt = 0.3 * m.max_time_step()
            wall = _bench_loop(lambda s: m.run(s, dt=dt), steps)
            per_pass = 2  # hydro + cleaning passes per super-step
        else:
            m = GridVlasov(n=n, nv=16)
            wall = _bench_loop(lambda s: m.run(s, dt=0.03), steps)
            per_pass = 1
            row["nv"] = 16
            row["phase_updates_per_sec"] = round(
                n ** 3 * 16 * steps / wall, 1)
        ups = n ** 3 * steps * per_pass / wall
        row["wall_s"] = round(wall, 4)
        row[f"{name}{n}_updates_per_sec"] = round(ups, 1)
    except Exception as e:  # noqa: BLE001 - null-on-failure discipline
        traceback.print_exc()
        row["error"] = f"{type(e).__name__}: {e}"
        row[f"{name}{n}_updates_per_sec"] = None
    return row


def ghost_split_leg(n, nz, steps):
    """Split vs full outer re-pass on the multi-device MHD model:
    bitwise parity asserted, row counts + wall per leg."""
    from dccrg_tpu import checkpoint
    from dccrg_tpu.models import GridMHD

    row = {"leg": "ghost_split", "n": n, "nz": nz, "steps": steps,
           "n_dev": len(jax.devices())}
    try:
        os.environ["DCCRG_OVERLAP"] = "1"
        out = {}
        for split in (False, True):
            os.environ["DCCRG_GHOST_SPLIT"] = "1" if split else "0"
            m = GridMHD(n=n, nz=nz)
            dt = 0.3 * m.max_time_step()
            wall = _bench_loop(lambda s: m.run(s, dt=dt), steps)
            # per-super-step recompute slots = hydro + cleaning pass:
            # one more instrumented super-step reads both passes'
            # counts (last_overlap reflects the latest compile)
            from dccrg_tpu.models.mhd import (MHD_ALL, MHD_BFIELD,
                                              MHD_HYDRO,
                                              make_mhd_pass_kernels)
            import jax.numpy as jnp

            hk, bk = make_mhd_pass_kernels()
            lam = jnp.float32(dt * n)
            counts = []
            for kern, exch in ((hk, MHD_HYDRO), (bk, MHD_BFIELD)):
                m.grid.run_steps(kern, MHD_ALL, MHD_ALL, 1,
                                 exchange_fields=exch,
                                 extra_args=(lam,))
                counts.append(dict(m.grid.last_overlap))
            rows_per_super = sum(c["rows_split"] for c in counts)
            rows_full = sum(c["rows_full"] for c in counts)
            out[split] = {
                "digest": checkpoint.state_digest(m.grid),
                "wall_s": wall,
                "rows": rows_per_super,
                "rows_full": rows_full,
                "mode": [c["mode"] for c in counts],
            }
        # the parity assertion: one extra super-step ran on each leg
        # with identical inputs, so the digests must still agree
        assert out[False]["digest"] == out[True]["digest"], (
            "ghost-split vs full outer re-pass digests diverged")
        row["outer_repass_rows_full"] = out[False]["rows"]
        row["outer_repass_rows_split"] = out[True]["rows"]
        row["ghost_split_rows_vs_baseline"] = round(
            out[False]["rows"] / max(1, out[True]["rows"]), 3)
        row["wall_full_s"] = round(out[False]["wall_s"], 4)
        row["wall_split_s"] = round(out[True]["wall_s"], 4)
        row["modes"] = {"full": out[False]["mode"],
                        "split": out[True]["mode"]}
        row["bitwise_parity"] = True
    except Exception as e:  # noqa: BLE001 - null-on-failure discipline
        traceback.print_exc()
        row["error"] = f"{type(e).__name__}: {e}"
        row["outer_repass_rows_full"] = None
        row["outer_repass_rows_split"] = None
        row["ghost_split_rows_vs_baseline"] = None
        row["bitwise_parity"] = None
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=16,
                    help="cube edge for the model legs (default 16)")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--split-n", type=int, default=8,
                    help="ghost-split leg edge (x --split-nz slabs)")
    ap.add_argument("--split-nz", type=int, default=80)
    ap.add_argument("--no-split", action="store_true",
                    help="skip the multi-device ghost-split leg")
    args = ap.parse_args(argv)

    devs = probe()
    if not devs:
        emit({"error": "no devices (probe failed)", "legs": None})
        return 0
    summary = {}
    for name in ("advect", "mhd", "vlasov"):
        row = model_leg(name, args.n, args.steps)
        emit(row)
        for k, v in row.items():
            if k.endswith("updates_per_sec"):
                summary[k] = v
    if not args.no_split:
        row = ghost_split_leg(args.split_n, args.split_nz,
                              max(4, args.steps // 8))
        emit(row)
        for k in ("outer_repass_rows_full", "outer_repass_rows_split",
                  "ghost_split_rows_vs_baseline"):
            summary[k] = row.get(k)
    emit({"summary": summary})
    return 0


if __name__ == "__main__":
    sys.exit(main())
