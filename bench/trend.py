#!/usr/bin/env python
"""Bench-history trend: merge the checked-in ``BENCH_r*.json`` rounds
into one metric-keyed trajectory table and flag regressions.

Each round file carries ``{"n": round, "parsed": {metric: value}}``
(the bench.py JSON summary). This tool lines the rounds up per metric
and flags the NEWEST round's value when it regresses more than
``--threshold`` (default 10%) against the best prior round — the
history was previously only eyeballable file-by-file.

Metric direction is inferred from the name: throughput-style keys
(``*updates_per_sec``, ``*runs_per_s``, ``value``, ``*vs_baseline``)
are higher-is-better; error/latency-style keys (``*l2_error*``,
``*_seconds``, ``*_s``) are lower-is-better; anything else (strings,
nulls, notes) is skipped. The streaming-intake saturation keys from
``bench/intake_bench.py`` ride these patterns unchanged:
``intake_drain_per_sec`` (higher) and
``intake_p99_queue_age_seconds`` (lower); a failed intake round
emits them as null, which load_rounds drops. So do the warm-start
keys from ``bench/warmstart_bench.py``:
``cold_first_dispatch_seconds`` / ``warm_first_dispatch_seconds``
(lower) and ``warm_speedup_vs_baseline`` (higher).

Usage: python bench/trend.py [BENCH_r*.json ...] [--threshold F]
       [--json] [--strict]
(default inputs: every BENCH_r*.json in the repo root; ``--strict``
exits 1 when any regression is flagged — the CI hook. Pure host-side
JSON, no jax; no timeout needed.)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

_HIGHER_PAT = re.compile(
    r"(updates_per_sec|runs_per_s|per_sec)$|^value$|vs_baseline$")
_LOWER_PAT = re.compile(r"l2_error|_seconds$|_ms$|(^|_)wall(_s)?$")


def metric_direction(name: str):
    """+1 = higher is better, -1 = lower is better, None = not a
    trended metric (notes, modes, sizes)."""
    if _HIGHER_PAT.search(name):
        return 1
    if _LOWER_PAT.search(name):
        return -1
    return None


def load_rounds(paths) -> list:
    """``[(round, {metric: value})]`` sorted by round number; files
    without a parsed payload (failed rounds) contribute an empty
    metric dict so the round still shows in the table."""
    rounds = []
    for p in paths:
        m = _ROUND_RE.search(os.path.basename(p))
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            print(f"# skipping {p}: {e}", file=sys.stderr)
            continue
        n = int(d.get("n", m.group(1) if m else len(rounds) + 1))
        parsed = d.get("parsed")
        metrics = {}
        if isinstance(parsed, dict):
            for k, v in parsed.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    metrics[k] = float(v)
        rounds.append((n, metrics))
    rounds.sort(key=lambda r: r[0])
    return rounds


def trajectory(rounds) -> dict:
    """``{metric: [(round, value)]}`` over every trended metric seen
    in any round (missing rounds simply absent)."""
    out: dict = {}
    for n, metrics in rounds:
        for k, v in metrics.items():
            if metric_direction(k) is None:
                continue
            out.setdefault(k, []).append((n, v))
    return out


def regressions(traj, threshold: float, newest_round=None) -> list:
    """``[{metric, round, value, best_prior, best_round, change}]``
    for every metric whose NEWEST value regresses more than
    ``threshold`` (fraction) against the best prior round. Metrics
    with fewer than two rounds have no prior to regress against,
    and a metric absent from ``newest_round`` (a renamed/removed
    bench leg) is historical — it must not flag a stale regression
    on every future run."""
    out = []
    for metric, points in sorted(traj.items()):
        if len(points) < 2:
            continue
        direction = metric_direction(metric)
        last_round, last = points[-1]
        if newest_round is not None and last_round != newest_round:
            continue
        prior = points[:-1]
        if direction > 0:
            best_round, best = max(prior, key=lambda p: p[1])
            if best <= 0:
                continue  # nothing was ever achieved to regress from
            change = (last - best) / abs(best)
            bad = change < -threshold
        else:
            best_round, best = min(prior, key=lambda p: p[1])
            if best <= 0:
                # a perfect (0.0) error baseline: ANY positive value
                # is an infinite regression — the one case a ratio
                # threshold cannot express, and exactly the class a
                # bitwise-parity metric regresses through
                change, bad = None, last > 0
            else:
                change = (last - best) / abs(best)
                bad = change > threshold
        if bad:
            out.append({"metric": metric, "round": last_round,
                        "value": last, "best_prior": best,
                        "best_round": best_round,
                        "change": (None if change is None
                                   else round(change, 4))})
    return out


def _fmt(v) -> str:
    if v is None:
        return "-"
    a = abs(v)
    return f"{v:.4g}" if (a >= 1e-3 and a < 1e7) or a == 0 else f"{v:.3e}"


def render_table(rounds, traj) -> str:
    ns = [n for n, _m in rounds]
    head = ["metric"] + [f"r{n:02d}" for n in ns] + ["dir"]
    lines = [" | ".join(head)]
    for metric, points in sorted(traj.items()):
        by_round = dict(points)
        row = [metric] + [_fmt(by_round.get(n)) for n in ns]
        row.append("^" if metric_direction(metric) > 0 else "v")
        lines.append(" | ".join(row))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="round files (default: repo-root "
                         "BENCH_r*.json)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="regression flag fraction vs the best prior "
                         "round (default 0.10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable form instead of "
                         "the table")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any regression is flagged")
    args = ap.parse_args(argv)
    files = args.files or sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_r*.json")))
    if not files:
        print("no BENCH_r*.json rounds found", file=sys.stderr)
        return 2
    rounds = load_rounds(files)
    traj = trajectory(rounds)
    newest = max((n for n, _m in rounds), default=None)
    regs = regressions(traj, args.threshold, newest_round=newest)
    if args.json:
        print(json.dumps({
            "rounds": [n for n, _m in rounds],
            "trajectory": {k: [[n, v] for n, v in pts]
                           for k, pts in sorted(traj.items())},
            "regressions": regs,
            "threshold": args.threshold}, indent=1, sort_keys=True))
    else:
        print(render_table(rounds, traj))
        print()
        if regs:
            for r in regs:
                delta = ("worse than a zero baseline"
                         if r["change"] is None
                         else f"{r['change']:+.1%}")
                print(f"REGRESSION {r['metric']}: r{r['round']:02d} "
                      f"{_fmt(r['value'])} is {delta} vs "
                      f"best prior r{r['best_round']:02d} "
                      f"{_fmt(r['best_prior'])}")
        else:
            print(f"no >{args.threshold:.0%} regressions vs the best "
                  "prior round")
    return 1 if (regs and args.strict) else 0


if __name__ == "__main__":
    sys.exit(main())
