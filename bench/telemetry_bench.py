"""Telemetry overhead on the hot step path: tracing must be ~free.

The 64^3 advection loop (the bench.py workhorse shape) is dispatched
repeatedly through ``Grid.run_steps`` — the exact boundary the
``grid.step`` span instruments — in two interleaved legs:

- ``trace_off`` — ``DCCRG_TRACE=0`` semantics: ``telemetry.span`` is
  the shared no-op singleton, so the step path is the pre-telemetry
  path plus ONE dict lookup;
- ``trace_on``  — spans recorded into the ring every dispatch (the
  ring is sized to hold the whole run; no flush inside the window).

Legs alternate (best-of pairs on the same warm state) so host noise
hits both equally. The bench ASSERTS the acceptance bounds: traced
overhead <= 2% of the untraced dispatch, untraced overhead
indistinguishable from noise (the no-op leg is compared against
itself across reps, and its spread bounds what "0%" means on this
host) — exit 1 on violation.

Run:  timeout -k 10 600 python bench/telemetry_bench.py
      [--n 64] [--steps 4] [--reps 7] [--dispatches 6]

JSON rows to stdout like the other bench emitters; PERF.md quotes the
summary row.
"""

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _mk_grid(n):
    from dccrg_tpu.grid import Grid, default_mesh
    from dccrg_tpu.resilience import probed_devices

    dev = probed_devices(platform="cpu")[0]
    g = (Grid(cell_data={"rho": jnp.float32})
         .set_initial_length((n, n, n))
         .set_periodic(True, True, True)
         .set_maximum_refinement_level(0)
         .set_neighborhood_length(1)
         .initialize(default_mesh([dev])))
    cells = g.plan.cells
    rng = np.random.default_rng(0)
    g.set("rho", cells,
          (rng.random(len(cells)) * 100.0).astype(np.float32))
    g.update_copies_of_remote_neighbors()
    return g


def _measure(g, kernel, steps, dispatches):
    """Seconds per dispatch (k fused steps each), device-synced."""
    t0 = time.perf_counter()
    for _ in range(dispatches):
        g.run_steps(kernel, ("rho",), ("rho",), steps,
                    extra_args=(jnp.float32(0.2),))
    jax.block_until_ready(g.data["rho"])
    return (time.perf_counter() - t0) / dispatches


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--steps", type=int, default=4,
                    help="fused steps per dispatch")
    ap.add_argument("--reps", type=int, default=15)
    ap.add_argument("--dispatches", type=int, default=4,
                    help="dispatches per timed window")
    args = ap.parse_args(argv)

    from dccrg_tpu import telemetry
    from dccrg_tpu.fleet import FLEET_KERNELS

    kernel = FLEET_KERNELS["advect_x"]
    g = _mk_grid(args.n)
    telemetry.configure(trace=False)
    _measure(g, kernel, args.steps, 2)  # compile + warm
    telemetry.configure(trace=True, ring=1 << 18)
    _measure(g, kernel, args.steps, 2)  # warm the traced path too
    telemetry.clear_trace()

    off, on = [], []
    for rep in range(args.reps):
        # interleaved AND order-alternated: host noise and any
        # monotonic drift (thermal, cache) hit both legs equally
        legs = [(False, off), (True, on)]
        if rep % 2:
            legs.reverse()
        for trace, acc in legs:
            telemetry.configure(trace=trace)
            acc.append(_measure(g, kernel, args.steps,
                                args.dispatches))
    n_events = len(telemetry.events())
    telemetry.configure(trace=False)
    telemetry.clear_trace()

    best_off, best_on = min(off), min(on)
    overhead_on = (best_on - best_off) / best_off
    # the no-op leg's own rep-to-rep spread is the noise floor this
    # host can resolve — "~0%" for the untraced path means within it
    noise = (max(off) - best_off) / best_off
    for name, leg in (("trace_off", off), ("trace_on", on)):
        print(json.dumps({
            "bench": "telemetry", "leg": name, "n": args.n,
            "steps_per_dispatch": args.steps,
            "best_s_per_dispatch": round(min(leg), 6),
            "reps_s": [round(v, 6) for v in leg]}), flush=True)
    print(json.dumps({"summary": {
        "n": args.n,
        "traced_overhead_pct": round(100 * overhead_on, 3),
        "noise_floor_pct": round(100 * noise, 3),
        "span_events_recorded": n_events,
        "bound_pct": 2.0}}), flush=True)

    ok = True
    if n_events < args.reps * args.dispatches:
        print(f"FAIL: tracing-on leg recorded {n_events} events "
              f"(expected >= {args.reps * args.dispatches})")
        ok = False
    if overhead_on > 0.02:
        print(f"FAIL: traced overhead {100 * overhead_on:.2f}% "
              "exceeds the 2% bound")
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
