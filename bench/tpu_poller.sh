#!/bin/bash
# Polls the axon TPU tunnel. Appends one line per probe to /tmp/tpu_poll.log;
# writes /tmp/tpu_up when a probe succeeds, then keeps polling (so a flap is visible).
while true; do
  ts=$(date +%s)
  out=$(timeout -k 5 90 python - <<'EOF' 2>&1
import jax
devs = jax.devices()
print("OK", devs)
EOF
)
  if [[ "$out" == OK* ]]; then
    echo "$ts UP $out" >> /tmp/tpu_poll.log
    echo "$ts" > /tmp/tpu_up
    # first contact: fire the full measurement battery once, so even
    # an unattended tunnel window is captured
    if [ ! -f /tmp/tpu_session_started ]; then
      touch /tmp/tpu_session_started
      nohup "$(dirname "$0")/chip_session.sh" \
        >> /tmp/tpu_poll.log 2>&1 &
    fi
  else
    echo "$ts DOWN $(echo "$out" | tail -1 | head -c 200)" >> /tmp/tpu_poll.log
  fi
  sleep 300
done
