#!/bin/bash
# Polls the axon TPU tunnel. Appends one line per probe to /tmp/tpu_poll.log;
# writes /tmp/tpu_up when a probe succeeds, then keeps polling (so a flap is visible).
#
# The probe runs through `python -m dccrg_tpu.resilience` (subprocess
# probe with hard-kill timeout escalation — the axon client is known to
# survive SIGTERM) with `timeout -k 5` as an outer belt, so a wedged
# tunnel can never wedge the poller.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
# A preempted poller (scheduler SIGTERM / operator ctrl-C) must leave
# no stale one-shot latch behind: a restarted poller should re-fire
# the measurement session on next contact instead of silently never
# measuring again. /tmp/tpu_up is status (last-contact record), not a
# lock — it stays.
trap 'echo "$(date +%s) PREEMPTED (poller got TERM/INT)" >> /tmp/tpu_poll.log; rm -f /tmp/tpu_session_started "/tmp/tpu_probe.$$"; exit 143' TERM INT
while true; do
  ts=$(date +%s)
  # probe in the background + `wait`: bash defers traps until the
  # foreground command exits, so a TERM during a 2-minute probe (or
  # the 5-minute sleep below) would otherwise go unanswered
  (cd "$REPO" && timeout -k 5 120 python -m dccrg_tpu.resilience --timeout 90 2>&1) > /tmp/tpu_probe.$$ &
  wait $! || true
  out=$(cat /tmp/tpu_probe.$$ 2>/dev/null); rm -f /tmp/tpu_probe.$$
  if echo "$out" | grep -q '^OK'; then
    echo "$ts UP $out" >> /tmp/tpu_poll.log
    echo "$ts" > /tmp/tpu_up
    # first contact: fire the full measurement battery once, so even
    # an unattended tunnel window is captured
    if [ ! -f /tmp/tpu_session_started ]; then
      touch /tmp/tpu_session_started
      nohup "$(dirname "$0")/chip_session.sh" \
        >> /tmp/tpu_poll.log 2>&1 &
    fi
  else
    echo "$ts DOWN $(echo "$out" | tail -1 | head -c 200)" >> /tmp/tpu_poll.log
  fi
  sleep 300 &
  wait $! || true
done
