#!/bin/bash
# Polls the axon TPU tunnel. Appends one line per probe to /tmp/tpu_poll.log;
# writes /tmp/tpu_up when a probe succeeds, then keeps polling (so a flap is visible).
#
# The probe runs through `python -m dccrg_tpu.resilience` (subprocess
# probe with hard-kill timeout escalation — the axon client is known to
# survive SIGTERM) with `timeout -k 5` as an outer belt, so a wedged
# tunnel can never wedge the poller.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
while true; do
  ts=$(date +%s)
  out=$(cd "$REPO" && timeout -k 5 120 python -m dccrg_tpu.resilience --timeout 90 2>&1)
  if echo "$out" | grep -q '^OK'; then
    echo "$ts UP $out" >> /tmp/tpu_poll.log
    echo "$ts" > /tmp/tpu_up
    # first contact: fire the full measurement battery once, so even
    # an unattended tunnel window is captured
    if [ ! -f /tmp/tpu_session_started ]; then
      touch /tmp/tpu_session_started
      nohup "$(dirname "$0")/chip_session.sh" \
        >> /tmp/tpu_poll.log 2>&1 &
    fi
  else
    echo "$ts DOWN $(echo "$out" | tail -1 | head -c 200)" >> /tmp/tpu_poll.log
  fi
  sleep 300
done
