"""Silent-data-corruption defense cost: what each SDC layer charges.

Three measurements over the same fleet workload (``--jobs`` jobs of
``--n``^3 cells, ``--steps`` steps, checkpoint cadence off so the
numbers are pure stepping):

- ``invariants`` — the in-program integrity invariants
  (``DCCRG_INTEGRITY=1``: fused entry/exit fingerprints +
  conservation sums + the per-quantum host compare) vs the same run
  with ``DCCRG_INTEGRITY=0`` (bitwise the pre-SDC program). The
  overhead target is <2% per step when on, 0 when off.
- ``audit`` — shadow-execution audits at ``--audit-every 1`` (the
  worst case: every tick re-executes one slot's quantum) vs audits
  off; reported per audit window so production cadences
  (``DCCRG_AUDIT_EVERY=50``-ish) can be extrapolated.
- ``dmr`` — ``FleetJob(redundancy=2)`` vs unreplicated: the
  throughput factor of running every step twice plus the per-quantum
  digest comparison (the expected factor is ~0.5x minus the compare;
  DMR is the always-on belt for jobs that cannot tolerate a sampled
  detector).

Every leg asserts bitwise digest parity with the solo baseline — a
defense layer that perturbs the answer would be worse than the
disease.

Run:  timeout -k 10 900 python bench/sdc_bench.py [--n 16]
      [--steps 32] [--jobs 16]

JSON rows to stdout like the other bench emitters; the summary row
carries the percentages PERF.md quotes.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def make_jobs(count, n, steps, redundancy=1):
    from dccrg_tpu.fleet import FleetJob

    return [FleetJob(f"b{i:04d}", length=(n, n, n), n_steps=steps,
                     params=(0.02 + 0.003 * (i % 7),), seed=i,
                     checkpoint_every=0, redundancy=redundancy)
            for i in range(count)]


def run_fleet_once(count, n, steps, *, integrity_on, audit_every=0,
                   redundancy=1, quantum=None):
    """One fleet pass under one SDC configuration; returns
    ``(wall_s, digests, audits)``."""
    from dccrg_tpu.fleet import GridBatch
    from dccrg_tpu.scheduler import FleetScheduler

    os.environ["DCCRG_INTEGRITY"] = "1" if integrity_on else "0"
    try:
        jobs = make_jobs(count, n, steps, redundancy)
        workdir = tempfile.mkdtemp(prefix="dccrg_sdc_bench_")
        try:
            sched = FleetScheduler(workdir, jobs, quantum=quantum,
                                   audit_every=audit_every)
            # warm every compile outside the window (program cache is
            # keyed by (bucket, capacity, integrity flag); the
            # fingerprint program is part of the integrity variant)
            sched._admit_pending()
            for bs in sched.buckets.values():
                for b in bs:
                    dummy = GridBatch(jobs[0], b.capacity)
                    dummy.step(np.ones(b.capacity, dtype=np.int32))
                    dummy.finite_slots()
                    if integrity_on:
                        dummy.fingerprint_slots()
            t0 = time.perf_counter()
            report = sched.run()
            wall = time.perf_counter() - t0
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
        assert all(r["status"] == "done" for r in report.values())
        assert all(r["trips"] == 0 for r in report.values()), \
            "false SDC alarm during the bench"
        return (wall, {m: r["digest"] for m, r in report.items()},
                sched.audits)
    finally:
        os.environ.pop("DCCRG_INTEGRITY", None)


def run_fleet(count, n, steps, legs, *, quantum=None, repeats=3):
    """INTERLEAVED best-of-``repeats``: every repeat runs every leg
    back to back, so host noise (this is a 1-core container) hits all
    configurations alike instead of whichever leg ran during a busy
    window. Returns ``{leg_name: (best_wall, digests, audits)}``."""
    best = {}
    for _ in range(repeats):
        for name, kw in legs.items():
            wall, digests, audits = run_fleet_once(
                count, n, steps, quantum=quantum, **kw)
            if name not in best or wall < best[name][0]:
                best[name] = (wall, digests, audits)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=16)
    ap.add_argument("--steps", type=int, default=96)
    ap.add_argument("--jobs", type=int, default=16)
    ap.add_argument("--quantum", type=int, default=None)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    # hang-proof backend probe before any jax work (like the other
    # benches: a wedged accelerator tunnel survives SIGTERM)
    from dccrg_tpu.resilience import safe_devices

    safe_devices(timeout=120, retries=1, platform="cpu")

    from dccrg_tpu.fleet import FleetJob, run_solo

    solo = {j.name: run_solo(FleetJob(
        j.name, length=j.length, n_steps=j.n_steps, params=j.params,
        seed=j.seed)) for j in make_jobs(args.jobs, args.n, args.steps)}

    legs = {
        "off": dict(integrity_on=False),
        "invariants": dict(integrity_on=True),
        "audit": dict(integrity_on=True, audit_every=1),
        "dmr": dict(integrity_on=True, redundancy=2),
    }
    out = run_fleet(args.jobs, args.n, args.steps, legs,
                    quantum=args.quantum, repeats=args.repeats)
    off, on, aud, dmr = (out[k][0] for k in
                         ("off", "invariants", "audit", "dmr"))
    n_aud = out["audit"][2]
    for name, (_w, d, _a) in out.items():
        assert d == solo, f"{name} leg lost bitwise parity with solo"

    steps_total = args.jobs * args.steps
    inv_pct = 100.0 * (on - off) / off
    rows = [
        {"leg": "baseline_integrity_off", "wall_s": round(off, 4),
         "ms_per_step": round(1e3 * off / steps_total, 4)},
        {"leg": "invariants_on", "wall_s": round(on, 4),
         "ms_per_step": round(1e3 * on / steps_total, 4),
         "overhead_pct": round(inv_pct, 2)},
        {"leg": "audit_every_tick", "wall_s": round(aud, 4),
         "audits": n_aud,
         "ms_per_audit_window": round(
             1e3 * (aud - on) / max(1, n_aud), 3)},
        {"leg": "dmr_redundancy_2", "wall_s": round(dmr, 4),
         "throughput_factor": round(on / dmr, 3)},
    ]
    for row in rows:
        print(json.dumps(row), flush=True)
    summary = {
        "jobs": args.jobs, "n": args.n, "steps": args.steps,
        "invariant_overhead_pct": round(inv_pct, 2),
        "audit_cost_ms_per_window": rows[2]["ms_per_audit_window"],
        "dmr_throughput_factor": rows[3]["throughput_factor"],
        "bitwise_parity": True,
    }
    print(json.dumps({"summary": summary}), flush=True)
    return summary


if __name__ == "__main__":
    main()
