"""Poisson solver benchmark: CG iterations/sec through the Pallas
matvec vs the XLA dense path (the BASELINE.json poisson leg).

Run on the chip: ``python bench/poisson_bench.py [--n 256]``.
On CPU hosts: ``BENCH_PLATFORM=cpu`` (interpret-mode kernel; numbers
only validate the flow, not performance).
"""

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    # device discovery through the hang-proof probe: a dead axon
    # tunnel fails fast instead of wedging the bench
    from dccrg_tpu.resilience import safe_devices

    devices = safe_devices(timeout=120, retries=1,
                           platform=os.environ.get("BENCH_PLATFORM") or None)
    on_tpu = devices[0].platform == "tpu"

    import numpy as np
    import jax.numpy as jnp

    from dccrg_tpu.models.poisson import DensePoissonSolver
    from dccrg_tpu.ops.poisson_kernel import make_laplacian_matvec

    n = args.n
    shape = (n, n, n)
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.random(shape).astype(np.float32))

    mv_pallas = make_laplacian_matvec(shape, interpret=not on_tpu)
    dense = DensePoissonSolver(shape)

    def dense_mv(x):
        arrays = {"p": x, "Ap": x}
        return dense._matvec(arrays)["Ap"]

    results = {"size": f"{n}^3", "platform": devices[0].platform}
    float(jnp.sum(p))  # pre-compile the sync reduction OUTSIDE timing
    for name, mv in (("pallas", mv_pallas), ("xla_dense", dense_mv)):
        out = mv(p)
        out.block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = mv(out)
        float(jnp.sum(out))  # forced scalar readback sync
        dt = time.perf_counter() - t0
        results[f"{name}_matvecs_per_sec"] = args.iters / dt
        results[f"{name}_cell_updates_per_sec"] = n**3 * args.iters / dt
    results["pallas_vs_dense"] = (
        results["pallas_matvecs_per_sec"] / results["xla_dense_matvecs_per_sec"]
    )
    print(json.dumps(results))


if __name__ == "__main__":
    main()
