#!/usr/bin/env python
"""Warm-start bench: cold vs warm first-dispatch latency over the
persistent compile cache (``dccrg_tpu/warmstart.py``).

Two child processes (fresh interpreters — the in-process program
cache and jax's in-memory executable cache would otherwise pollute
the warm measurement) share one ``DCCRG_COMPILE_CACHE`` dir:

- ``cold`` — empty cache: every bucket's first dispatch pays the
  trace+compile; the manifest records land.
- ``warm`` — the restart: the pool pre-compiles every manifested
  bucket off the serve clock, the first dispatch must pay none of it.

Reported (the trend.py keys):

- ``cold_first_dispatch_seconds`` / ``warm_first_dispatch_seconds``
  — the WORST per-bucket first-dispatch latency each side (lower is
  better; the ``seconds`` the scheduler's first-dispatch hook
  measures, i.e. what a rejoining host's first job actually waits),
- ``warm_speedup_vs_baseline`` — cold/warm (higher is better; the
  ISSUE bound is >=10x, asserted by tests/mp_harness.py rejoin_warm,
  merely reported here),
- ``compiles_avoided`` — programs the warm side served from the pool
  instead of compiling.

JSON rows go to stdout like the other bench emitters; on any failure
the summary still prints with null metric values so ``bench/trend.py``
skips (rather than crashes on) the round.

Run:  timeout -k 10 600 python bench/warmstart_bench.py [--buckets 3]
      [--steps 16]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def child(args) -> int:
    """One serve leg (fresh interpreter): build the job set, serve it
    through FleetScheduler with the warm pool on, print a JSON row
    with the worst first-dispatch latency."""
    os.environ["DCCRG_COMPILE_CACHE"] = args.cache
    import jax

    jax.config.update("jax_platforms", "cpu")
    from dccrg_tpu.fleet import FleetJob
    from dccrg_tpu.scheduler import FleetScheduler

    lengths = [(8, 8, 8 + 2 * i) for i in range(args.buckets)]
    jobs = [FleetJob(f"b{i}", length=ln, n_steps=args.steps,
                     params=(0.05,), seed=args.seed + i,
                     checkpoint_every=0)
            for i, ln in enumerate(lengths)]
    sched = FleetScheduler(args.store, jobs)
    pool = sched.warm
    assert pool is not None, "no warm pool (DCCRG_COMPILE_CACHE set?)"
    if args.phase == "warm" and pool._worker is not None:
        # the pre-compile sweep runs off the serve clock
        t0 = time.perf_counter()
        assert pool._worker.wait(300)
        assert pool._worker.error is None, pool._worker.error
        prewarm_s = time.perf_counter() - t0
    else:
        prewarm_s = 0.0
    firsts = {}
    orig = pool.note_dispatch

    def spy(batch, seconds):
        firsts.setdefault(batch.key, float(seconds))
        return orig(batch, seconds)

    pool.note_dispatch = spy
    t0 = time.perf_counter()
    report = sched.run()
    wall = time.perf_counter() - t0
    assert {r["status"] for r in report.values()} == {"done"}, report
    print(json.dumps({
        "phase": args.phase,
        "first_dispatch_s": round(max(firsts.values()), 6),
        "served_warm": len(pool._served),
        "prewarm_s": round(prewarm_s, 4),
        "wall_s": round(wall, 4),
        "digests": {n: r["digest"] for n, r in report.items()},
    }), flush=True)
    return 0


def _spawn_child(args, phase, cache, store):
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--phase", phase, "--cache", cache, "--store", store,
           "--buckets", str(args.buckets), "--steps", str(args.steps),
           "--seed", str(args.seed)]
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=540)
    if out.returncode != 0:
        raise RuntimeError(f"{phase} child rc {out.returncode}:\n"
                           f"{out.stdout[-2000:]}\n{out.stderr[-2000:]}")
    row = json.loads(out.stdout.strip().splitlines()[-1])
    print(json.dumps(row), flush=True)
    return row


def run_bench(args) -> int:
    tmp = tempfile.mkdtemp(prefix="warmstart_bench_")
    try:
        cache = str(Path(tmp) / "cache")
        cold = _spawn_child(args, "cold", cache,
                            str(Path(tmp) / "ck_cold"))
        warm = _spawn_child(args, "warm", cache,
                            str(Path(tmp) / "ck_warm"))
        c, w = cold["first_dispatch_s"], warm["first_dispatch_s"]
        ok = (warm["served_warm"] >= args.buckets
              and warm["digests"] == cold["digests"] and w < c)
        summary = {
            "cold_first_dispatch_seconds": c if ok else None,
            "warm_first_dispatch_seconds": w if ok else None,
            "warm_speedup_vs_baseline": (
                round(c / max(w, 1e-9), 2) if ok else None),
            "compiles_avoided": warm["served_warm"],
            "prewarm_s": warm["prewarm_s"],
            "ok": ok,
            "note": ("%d buckets; warm served from the pool, digests "
                     "bitwise-equal cold" % args.buckets if ok
                     else "warm leg not warm / digest mismatch"),
        }
    except Exception as e:  # null metrics: trend.py skips, not crashes
        summary = {"cold_first_dispatch_seconds": None,
                   "warm_first_dispatch_seconds": None,
                   "warm_speedup_vs_baseline": None,
                   "ok": False, "error": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps({"summary": summary}), flush=True)
    return 0 if summary.get("ok") else 1


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--phase", default="cold",
                    choices=("cold", "warm"))
    ap.add_argument("--cache", default="")
    ap.add_argument("--store", default="")
    ap.add_argument("--buckets", type=int, default=3)
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.child:
        return child(args)

    from dccrg_tpu.resilience import safe_devices
    if safe_devices(timeout=120, retries=1, platform="cpu") is None:
        print(json.dumps({"summary": {
            "cold_first_dispatch_seconds": None,
            "warm_first_dispatch_seconds": None,
            "warm_speedup_vs_baseline": None,
            "ok": False, "error": "device probe failed"}}))
        return 1
    return run_bench(args)


if __name__ == "__main__":
    sys.exit(main())
