"""Strong/weak scaling of the general-Grid fused step loop over the
virtual CPU mesh — the reference's scalability suite role
(tests/scalability, tests/game_of_life/scalability*.cpp) for the
framework path. The absolute numbers are CPU-host numbers; the point
is the scaling shape of exchange+stencil+apply as devices grow.

Run: python bench/grid_scaling.py [--n 64] [--steps 10]
"""

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from dccrg_tpu.models.advection import GridAdvection  # noqa: E402


_devices = None


def _safe_device_list():
    # hang-proof probe (ROUND6 gotcha): never call raw jax.devices()
    # first from a bench script — a dead accelerator tunnel hangs it;
    # probed once in a subprocess, then cached
    global _devices
    if _devices is None:
        from dccrg_tpu.resilience import safe_devices

        _devices = safe_devices(timeout=120, retries=1, platform="cpu")
    return _devices


def run_once(n, nz, n_dev, steps):
    mesh = Mesh(np.array(_safe_device_list()[:n_dev]), ("dev",))
    s = GridAdvection(n=n, nz=nz, mesh=mesh)
    dt = 0.5 * s.max_time_step()
    s.run(1, dt)
    s.checksum()
    t0 = time.perf_counter()
    s.run(steps, dt)
    s.checksum()
    el = time.perf_counter() - t0
    return n * n * nz * steps / el


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()
    results = []
    base = None
    for n_dev in (1, 2, 4, 8):
        # strong scaling: fixed problem
        strong = run_once(args.n, args.n, n_dev, args.steps)
        # weak scaling: nz grows with devices
        weak = run_once(args.n, max(4, args.n // 8) * n_dev, n_dev, args.steps)
        if base is None:
            base = strong
        results.append({
            "devices": n_dev,
            "strong_updates_per_s": round(strong),
            "strong_speedup": round(strong / base, 2),
            "weak_updates_per_s": round(weak),
        })
        print(json.dumps(results[-1]))
    return results


if __name__ == "__main__":
    main()
