"""Checkpoint cost per periodic save: full keyframes vs dirty-field
deltas (the ROADMAP "Incremental checkpoints" item's measuring stick).

The workload is the production shape delta saves exist for — a step
loop over a multi-field schema where only the stepped field changes
between saves (the Vlasov-style wide per-cell payload of the
reference's home domain stays static): each periodic save is timed
and sized in both modes, ``full`` (``DCCRG_DELTA=0``: every save a
keyframe, byte-for-byte the pre-delta behavior — asserted against a
direct ``resilience.save_checkpoint``) and ``delta``
(``CheckpointStore.save`` dirty-field chains, keyframe cadence
``--keyframe-every``).  The final delta chain is materialized and
compared bitwise against a direct full save — the bench doubles as an
end-to-end integrity check.

Run:  timeout -k 10 600 python bench/ckpt_bench.py [--n 32] [--saves 8]

JSON rows go to stdout like the other bench emitters; the summary row
carries the bytes-per-save table PERF.md quotes (acceptance: the
delta rows >= 10x fewer bytes than the full rows).
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import dccrg_tpu as dt  # noqa: E402

# the multi-field scenario: one narrow stepped field, one wide static
# per-cell payload (Vlasov-style), one static tag — the step loop
# dirties ONLY "rho", so a delta carries the 16 B/cell offset-pair
# table + 4 B/cell of rho against the full format's ~276 B/cell
SCHEMA = {"rho": jnp.float32, "f": ((64,), jnp.float32),
          "tag": jnp.int32}


def _mk_grid(n, seed=0):
    g = (dt.Grid(cell_data=SCHEMA)
         .set_initial_length((n, n, n))
         .set_maximum_refinement_level(0)
         .set_neighborhood_length(1)
         .set_periodic(True, True, True)
         .initialize())
    rng = np.random.default_rng(seed)
    cells = g.plan.cells
    for name, (shape, dtype) in g.fields.items():
        g.set(name, cells,
              (rng.random((len(cells),) + shape) * 100).astype(dtype))
    g.update_copies_of_remote_neighbors()
    return g


def _kernel(c, nbr, offs, mask):
    return {"rho": 0.5 * c["rho"] + 0.125 * jnp.sum(
        jnp.where(mask, nbr["rho"], 0.0), axis=1)}


def run_mode(mode, n, saves, keyframe_every, workdir):
    """One measured pass: a step loop with a periodic save per step,
    in ``full`` (DCCRG_DELTA=0) or ``delta`` mode. Returns the rows."""
    from dccrg_tpu import resilience, supervise

    os.environ["DCCRG_DELTA"] = "0" if mode == "full" else "1"
    store_dir = os.path.join(workdir, mode)
    g = _mk_grid(n)
    store = supervise.CheckpointStore(store_dir,
                                      keyframe_every=keyframe_every)
    rows = []
    for step in range(saves):
        if step:
            g.run_steps(_kernel, ["rho"], ["rho"], 1)
        t0 = time.perf_counter()
        path = store.save(g, step)
        wall = time.perf_counter() - t0
        kind = ("delta" if path.endswith(resilience.DELTA_SUFFIX)
                else "keyframe")
        row = {"mode": mode, "step": step, "kind": kind,
               "bytes": os.path.getsize(path),
               "wall_s": round(wall, 4)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    final = store.list()[0][1]
    if mode == "full":
        # DCCRG_DELTA=0 must be byte-for-byte the pre-delta behavior
        direct = os.path.join(workdir, "direct.dc")
        resilience.save_checkpoint(g, direct)
        with open(final, "rb") as a, open(direct, "rb") as b:
            assert a.read() == b.read(), \
                "DCCRG_DELTA=0 save differs from a direct full save"
    else:
        # the chain must reconstruct the exact full bytes
        assert any(r["kind"] == "delta" for r in rows), \
            "delta mode produced no delta saves"
        direct = os.path.join(workdir, "direct_delta.dc")
        resilience.save_checkpoint(g, direct)
        if final.endswith(resilience.DELTA_SUFFIX):
            out = final + ".chain.bench"
            resilience.materialize_chain(final, out, g.fields)
            with open(out, "rb") as a, open(direct, "rb") as b:
                assert a.read() == b.read(), \
                    "materialized delta chain != direct full save"
            os.unlink(out)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=32,
                    help="grid edge length (n^3 level-0 cells)")
    ap.add_argument("--saves", type=int, default=8,
                    help="periodic saves per mode")
    ap.add_argument("--keyframe-every", type=int, default=8)
    args = ap.parse_args()

    # hang-proof backend probe before any jax work (like the other
    # benches: a wedged accelerator tunnel survives SIGTERM)
    from dccrg_tpu.resilience import safe_devices

    safe_devices(timeout=120, retries=1, platform="cpu")

    workdir = tempfile.mkdtemp(prefix="dccrg_ckpt_bench_")
    try:
        rows = []
        for mode in ("full", "delta"):
            rows += run_mode(mode, args.n, args.saves,
                             args.keyframe_every, workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    full = [r for r in rows if r["mode"] == "full"]
    delt = [r for r in rows if r["mode"] == "delta"
            and r["kind"] == "delta"]
    all_delta_mode = [r for r in rows if r["mode"] == "delta"]
    mean = lambda rs, k: sum(r[k] for r in rs) / max(1, len(rs))  # noqa: E731
    summary = {
        "cells": args.n ** 3, "saves": args.saves,
        "keyframe_every": args.keyframe_every,
        "full_bytes_per_save": round(mean(full, "bytes")),
        "delta_bytes_per_save": round(mean(delt, "bytes")),
        "chain_mean_bytes_per_save":
            round(mean(all_delta_mode, "bytes")),
        "full_wall_s_per_save": round(mean(full, "wall_s"), 4),
        "delta_wall_s_per_save": round(mean(delt, "wall_s"), 4),
        "bytes_ratio_full_over_delta":
            round(mean(full, "bytes") / max(1.0, mean(delt, "bytes")), 1),
    }
    print(json.dumps({"summary": summary}), flush=True)
    return summary


if __name__ == "__main__":
    main()
