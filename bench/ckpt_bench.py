"""Checkpoint cost per periodic save: full keyframes vs dirty-field
deltas (the ROADMAP "Incremental checkpoints" item's measuring stick).

The workload is the production shape delta saves exist for — a step
loop over a multi-field schema where only the stepped field changes
between saves (the Vlasov-style wide per-cell payload of the
reference's home domain stays static): each periodic save is timed
and sized in both modes, ``full`` (``DCCRG_DELTA=0``: every save a
keyframe, byte-for-byte the pre-delta behavior — asserted against a
direct ``resilience.save_checkpoint``) and ``delta``
(``CheckpointStore.save`` dirty-field chains, keyframe cadence
``--keyframe-every``).  The final delta chain is materialized and
compared bitwise against a direct full save — the bench doubles as an
end-to-end integrity check.

``--overlap`` runs the async-save leg instead: the same periodic-save
loop with the next quantum's dispatch between save and drain,
measuring how much of each save's wall the serving loop actually
loses — synchronously (the whole save call) vs ``DCCRG_ASYNC_SAVE=1``
(the snapshot+submit call plus the residual drain after the quantum).
The saved files are asserted bitwise identical between the two legs
(the negative pin and the async parity pin in one comparison).
Acceptance: >= 70% of the save wall overlapped with the next
quantum's dispatch.

Run:  timeout -k 10 600 python bench/ckpt_bench.py [--n 32] [--saves 8]

JSON rows go to stdout like the other bench emitters; the summary row
carries the bytes-per-save table PERF.md quotes (acceptance: the
delta rows >= 10x fewer bytes than the full rows). The --overlap
summary's ``ckpt_stall_sync_seconds``/``ckpt_stall_async_seconds``
keys follow bench/trend.py's lower-is-better naming.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import dccrg_tpu as dt  # noqa: E402

# the multi-field scenario: one narrow stepped field, one wide static
# per-cell payload (Vlasov-style), one static tag — the step loop
# dirties ONLY "rho", so a delta carries the 16 B/cell offset-pair
# table + 4 B/cell of rho against the full format's ~276 B/cell
SCHEMA = {"rho": jnp.float32, "f": ((64,), jnp.float32),
          "tag": jnp.int32}


def _mk_grid(n, seed=0):
    g = (dt.Grid(cell_data=SCHEMA)
         .set_initial_length((n, n, n))
         .set_maximum_refinement_level(0)
         .set_neighborhood_length(1)
         .set_periodic(True, True, True)
         .initialize())
    rng = np.random.default_rng(seed)
    cells = g.plan.cells
    for name, (shape, dtype) in g.fields.items():
        g.set(name, cells,
              (rng.random((len(cells),) + shape) * 100).astype(dtype))
    g.update_copies_of_remote_neighbors()
    return g


def _kernel(c, nbr, offs, mask):
    return {"rho": 0.5 * c["rho"] + 0.125 * jnp.sum(
        jnp.where(mask, nbr["rho"], 0.0), axis=1)}


def run_mode(mode, n, saves, keyframe_every, workdir):
    """One measured pass: a step loop with a periodic save per step,
    in ``full`` (DCCRG_DELTA=0) or ``delta`` mode. Returns the rows."""
    from dccrg_tpu import resilience, supervise

    os.environ["DCCRG_DELTA"] = "0" if mode == "full" else "1"
    store_dir = os.path.join(workdir, mode)
    g = _mk_grid(n)
    store = supervise.CheckpointStore(store_dir,
                                      keyframe_every=keyframe_every)
    rows = []
    for step in range(saves):
        if step:
            g.run_steps(_kernel, ["rho"], ["rho"], 1)
        t0 = time.perf_counter()
        path = store.save(g, step)
        wall = time.perf_counter() - t0
        kind = ("delta" if path.endswith(resilience.DELTA_SUFFIX)
                else "keyframe")
        row = {"mode": mode, "step": step, "kind": kind,
               "bytes": os.path.getsize(path),
               "wall_s": round(wall, 4)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    final = store.list()[0][1]
    if mode == "full":
        # DCCRG_DELTA=0 must be byte-for-byte the pre-delta behavior
        direct = os.path.join(workdir, "direct.dc")
        resilience.save_checkpoint(g, direct)
        with open(final, "rb") as a, open(direct, "rb") as b:
            assert a.read() == b.read(), \
                "DCCRG_DELTA=0 save differs from a direct full save"
    else:
        # the chain must reconstruct the exact full bytes
        assert any(r["kind"] == "delta" for r in rows), \
            "delta mode produced no delta saves"
        direct = os.path.join(workdir, "direct_delta.dc")
        resilience.save_checkpoint(g, direct)
        if final.endswith(resilience.DELTA_SUFFIX):
            out = final + ".chain.bench"
            resilience.materialize_chain(final, out, g.fields)
            with open(out, "rb") as a, open(direct, "rb") as b:
                assert a.read() == b.read(), \
                    "materialized delta chain != direct full save"
            os.unlink(out)
    return rows


# ---------------------------------------------------------------------
# the --overlap leg: save wall overlapped with the next quantum
# ---------------------------------------------------------------------

def _sha(path):
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _async_write_wall_total():
    from dccrg_tpu import telemetry

    tot = 0.0
    for (nm, _lab), h in telemetry.registry().histograms.items():
        if nm == "dccrg_ckpt_async_write_seconds":
            tot += h.sum_seconds
    return tot


def run_overlap(n, saves, quantum_steps, workdir):
    """Periodic keyframe saves with the next quantum's dispatch
    between save and drain: the serving loop's actual per-save stall,
    synchronous vs DCCRG_ASYNC_SAVE=1, files bitwise identical. The
    async write's TRUE wall is measured on the writer thread
    (``dccrg_ckpt_async_write_seconds``), so the overlap fraction is
    (write wall not spent blocking the caller) / save wall — a short
    write under a long dispatch reads as a short save fully
    overlapped, not as a long one."""
    from dccrg_tpu import supervise

    def leg(async_on):
        os.environ["DCCRG_ASYNC_SAVE"] = "1" if async_on else "0"
        d = os.path.join(workdir, "async" if async_on else "sync")
        g = _mk_grid(n)
        g.run_steps(_kernel, ["rho"], ["rho"], quantum_steps)  # warm
        jax.block_until_ready(g.data["rho"])
        store = supervise.CheckpointStore(d, stem="ov")
        rows = []
        for i in range(saves):
            w0 = _async_write_wall_total()
            t0 = time.perf_counter()
            store.save(g, i, force_keyframe=True)
            submit = time.perf_counter() - t0
            t1 = time.perf_counter()
            g.run_steps(_kernel, ["rho"], ["rho"], quantum_steps)
            jax.block_until_ready(g.data["rho"])
            dispatch = time.perf_counter() - t1
            t2 = time.perf_counter()
            store.drain()
            residual = time.perf_counter() - t2
            # the save's wall: the blocking submit (snapshot/pull)
            # plus the write's wall as measured ON the writer thread
            # (sync mode: the save call is the whole wall)
            write_wall = (_async_write_wall_total() - w0 if async_on
                          else 0.0)
            save_wall = submit + write_wall if async_on else submit
            # the write ran concurrently with dispatch except for the
            # tail the caller had to block for (the residual drain)
            overlapped = max(0.0, write_wall - residual)
            rows.append({"save_call_s": submit, "dispatch_s": dispatch,
                         "drain_s": residual,
                         "stall_s": submit + residual,
                         "write_wall_s": write_wall,
                         "save_wall_s": save_wall,
                         "overlapped_s": overlapped if async_on else 0.0})
        digests = {os.path.basename(p): _sha(p) for _s, p in store.list()}
        return rows, digests

    sync_rows, sync_digests = leg(False)
    async_rows, async_digests = leg(True)
    os.environ.pop("DCCRG_ASYNC_SAVE", None)
    assert sync_digests == async_digests, \
        "DCCRG_ASYNC_SAVE=1 checkpoints differ bitwise from sync saves"
    mean = lambda rs, k: sum(r[k] for r in rs) / max(1, len(rs))  # noqa: E731
    wall_sync = mean(sync_rows, "stall_s")
    stall_async = mean(async_rows, "stall_s")
    # the acceptance metric: what fraction of the async save's wall
    # (blocking submit + the write's true writer-thread wall) ran
    # CONCURRENTLY with the next quantum's dispatch — i.e. everything
    # except the submit and the residual drain tail. The separate
    # stall-reduction ratio is the serving-loop payoff.
    overlap_frac = (mean(async_rows, "overlapped_s")
                    / max(mean(async_rows, "save_wall_s"), 1e-9))
    summary = {
        "cells": n ** 3, "saves": saves,
        "quantum_steps": quantum_steps,
        "ckpt_stall_sync_seconds": round(wall_sync, 4),
        "ckpt_stall_async_seconds": round(stall_async, 4),
        "async_submit_s_per_save": round(mean(async_rows,
                                              "save_call_s"), 4),
        "async_write_wall_s_per_save": round(mean(async_rows,
                                                  "write_wall_s"), 4),
        "async_residual_drain_s_per_save": round(mean(async_rows,
                                                      "drain_s"), 4),
        "dispatch_s_per_quantum": round(mean(async_rows,
                                             "dispatch_s"), 4),
        "save_wall_overlap_frac": round(overlap_frac, 3),
        "stall_reduction_frac": round(
            max(0.0, 1.0 - stall_async / max(wall_sync, 1e-9)), 3),
        "files_bitwise_identical": True,
    }
    for r in sync_rows:
        print(json.dumps(dict(r, mode="sync")), flush=True)
    for r in async_rows:
        print(json.dumps(dict(r, mode="async")), flush=True)
    print(json.dumps({"overlap_summary": summary}), flush=True)
    return summary


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=32,
                    help="grid edge length (n^3 level-0 cells)")
    ap.add_argument("--saves", type=int, default=8,
                    help="periodic saves per mode")
    ap.add_argument("--keyframe-every", type=int, default=8)
    ap.add_argument("--overlap", action="store_true",
                    help="measure per-save serving stall sync vs "
                         "DCCRG_ASYNC_SAVE=1 (files asserted bitwise "
                         "identical)")
    ap.add_argument("--quantum-steps", type=int, default=48,
                    help="steps dispatched between an async save's "
                         "submit and its drain (the overlap window)")
    args = ap.parse_args()

    # hang-proof backend probe before any jax work (like the other
    # benches: a wedged accelerator tunnel survives SIGTERM)
    from dccrg_tpu.resilience import safe_devices

    safe_devices(timeout=120, retries=1, platform="cpu")

    workdir = tempfile.mkdtemp(prefix="dccrg_ckpt_bench_")
    try:
        if args.overlap:
            return run_overlap(args.n, args.saves, args.quantum_steps,
                               workdir)
        rows = []
        for mode in ("full", "delta"):
            rows += run_mode(mode, args.n, args.saves,
                             args.keyframe_every, workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    full = [r for r in rows if r["mode"] == "full"]
    delt = [r for r in rows if r["mode"] == "delta"
            and r["kind"] == "delta"]
    all_delta_mode = [r for r in rows if r["mode"] == "delta"]
    mean = lambda rs, k: sum(r[k] for r in rs) / max(1, len(rs))  # noqa: E731
    summary = {
        "cells": args.n ** 3, "saves": args.saves,
        "keyframe_every": args.keyframe_every,
        "full_bytes_per_save": round(mean(full, "bytes")),
        "delta_bytes_per_save": round(mean(delt, "bytes")),
        "chain_mean_bytes_per_save":
            round(mean(all_delta_mode, "bytes")),
        "full_wall_s_per_save": round(mean(full, "wall_s"), 4),
        "delta_wall_s_per_save": round(mean(delt, "wall_s"), 4),
        "bytes_ratio_full_over_delta":
            round(mean(full, "bytes") / max(1.0, mean(delt, "bytes")), 1),
    }
    print(json.dumps({"summary": summary}), flush=True)
    return summary


if __name__ == "__main__":
    main()
