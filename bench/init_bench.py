"""Grid construction speed (the reference's tests/init suite).

Times Grid.initialize at growing sizes on the host (structure building
is host work in this design; the reference's equivalent is
create_level_0_cells + initialize_neighbors, dccrg.hpp:8089-8420).

Run: python bench/init_bench.py [--max 256]
"""

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# --devices N (parsed pre-jax): virtual CPU device count, so the
# multi-device closed-form rows below measure a real n-device mesh
_n_dev = 1
for _i, _a in enumerate(sys.argv):
    if _a == "--devices":
        _n_dev = int(sys.argv[_i + 1])
    elif _a.startswith("--devices="):
        _n_dev = int(_a.split("=", 1)[1])
if _n_dev > 1:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_n_dev}"
        )

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import dccrg_tpu as dt  # noqa: E402


def time_init(n, partition):
    t0 = time.time()
    g = (
        dt.Grid(cell_data={"density": jnp.float32})
        .set_initial_length((n, n, n))
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .initialize(partition=partition)
    )
    dt_s = time.time() - t0
    n_cells = len(g.plan.cells)
    del g
    return dt_s, n_cells


def time_amr_commit(n):
    """One AMR commit on an n^3 grid: refine a z-slab of 1/64 of the
    level-0 cells (the hybrid builder's hard set is the slab surface),
    then a second commit on the already-refined grid."""
    g = (
        dt.Grid(cell_data={"density": jnp.float32})
        .set_initial_length((n, n, n))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .initialize()
    )
    cells = g.plan.cells
    nref = len(cells) // 64
    for c in cells[:nref]:
        g.refine_completely(c)
    t0 = time.time()
    g.stop_refining()
    first = time.time() - t0
    cells = g.plan.cells
    lvl0 = cells[cells <= np.uint64(n) ** 3]
    for c in lvl0[-nref:]:
        g.refine_completely(c)
    t0 = time.time()
    g.stop_refining()
    second = time.time() - t0
    n_cells = len(g.plan.cells)
    del g
    return first, second, n_cells


def time_field_init(n):
    """GridAdvection construction: structure + ON-device field init
    (density/vx/vy synthesized from the sharded row-id array — no host
    center arrays; the reference's initialize.hpp:36-80 one-pass
    equivalent). Reported both as the constructor wall time (dispatch)
    and with the field computation synced, which on the CPU backend
    executes the trig on host cores; on TPU it runs on chip."""
    from dccrg_tpu.models.advection import GridAdvection

    t0 = time.time()
    a = GridAdvection(n=n)
    construct = time.time() - t0
    for f in a.grid.data.values():
        f.block_until_ready()
    synced = time.time() - t0
    n_cells = len(a.grid.plan.cells)
    del a
    return construct, synced, n_cells


def time_multi_device_init(n, n_dev):
    """n-device uniform init + first roll plan: block partitions take
    the closed-form multi-device plan (no dense tables); morton takes
    the dense path — the two rows bound the closed-form win."""
    from jax.sharding import Mesh

    from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID

    # probe through the hang-proof subprocess path (ROUND6 gotcha: a
    # wedged accelerator tunnel survives SIGTERM; raw jax.devices()
    # can block forever even when this script targets the CPU backend
    # via a pre-imported, mis-pointed jax)
    from dccrg_tpu.resilience import safe_devices

    devices = safe_devices(timeout=120, retries=1, platform="cpu")
    if len(devices) < n_dev:
        raise RuntimeError(
            f"--devices {n_dev} requested but only {len(devices)} "
            "devices exist (inherited XLA_FLAGS already pins "
            "xla_force_host_platform_device_count?)"
        )
    out = []
    mesh = Mesh(np.array(devices[:n_dev]), ("dev",))
    for part in ("block", "morton"):
        t0 = time.time()
        g = (
            dt.Grid(cell_data={"density": jnp.float32})
            .set_initial_length((n, n, n))
            .set_maximum_refinement_level(0)
            .set_neighborhood_length(0)
            .initialize(mesh, partition=part)
        )
        hood = g.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
        hood.roll_plan(g.plan.L)
        secs = time.time() - t0
        closed = hood.closed_form is not None
        out.append({
            "size": f"{n}^3 x {n_dev} devices", "partition": part,
            "seconds": round(secs, 2), "closed_form": closed,
        })
        del g
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max", type=int, default=256)
    ap.add_argument("--amr-max", type=int, default=128)
    ap.add_argument("--devices", type=int, default=1)
    args = ap.parse_args()
    sizes = [s for s in (64, 128, 256, 512) if s <= args.max]
    results = []
    for n in sizes:
        for part in ("block", "morton"):
            # best of 2: the first touch of a fresh heap region pays
            # page faults that later builds (and long-running apps)
            # amortize away
            secs, n_cells = min(time_init(n, part) for _ in range(2))
            results.append({
                "size": f"{n}^3", "partition": part, "seconds": round(secs, 2),
                "cells_per_s": round(n_cells / secs),
            })
            print(json.dumps(results[-1]))
    construct, synced, n_cells = time_field_init(min(args.max, 256))
    results.append({
        "size": f"GridAdvection {min(args.max, 256)}^3 field init",
        "construct_s": round(construct, 2), "synced_s": round(synced, 2),
        "cells": n_cells,
    })
    print(json.dumps(results[-1]))
    if args.devices > 1:
        for row in time_multi_device_init(min(args.max, 256), args.devices):
            results.append(row)
            print(json.dumps(row))
    for n in (s for s in (64, 128, 256) if s <= args.amr_max):
        first, second, n_cells = time_amr_commit(n)
        results.append({
            "size": f"{n}^3 + 1/64 refined", "amr_commit_s": round(first, 2),
            "amr_recommit_s": round(second, 2), "cells": n_cells,
        })
        print(json.dumps(results[-1]))
    return results


if __name__ == "__main__":
    main()
