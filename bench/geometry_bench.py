#!/usr/bin/env python
"""Geometry lookup micro-benchmark.

The reference's only in-tree performance numbers are geometry lookup
throughputs (tests/geometry README, recorded in BASELINE.md):

  Cartesian  cell size lookup:   1.24-1.39 s / 1e8 cells  (~7.7e7 /s)
  Cartesian  cell position:      3.7-4.79  s / 1e8 cells  (~2.4e7 /s)
  Stretched  cell size lookup:   3.6-4.1   s / 1e8 cells  (~2.6e7 /s)
  Stretched  cell position:      7.99-11.36 s / 1e8 cells (~1.0e7 /s)

(AMD Phenom II X6 1075T, one core.)  This driver measures the same
lookups through dccrg_tpu's vectorized geometry layer and prints one
JSON line per metric with the speedup over the reference midpoint.

Run:  timeout -k 10 600 python bench/geometry_bench.py [n_lookups]

(No safe_devices probe: this bench is pure numpy/ctypes host code and
never touches jax, so there is no accelerator tunnel to hang on.)
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import ctypes

import numpy as np

# keep large result buffers on the heap so repeated calls reuse pages
# instead of page-faulting a fresh mmap every time (the lookups
# themselves are ~10x faster than the fault-in otherwise)
try:
    ctypes.CDLL("libc.so.6").mallopt(-3, 1 << 30)  # M_MMAP_THRESHOLD
except OSError:
    pass

from dccrg_tpu.geometry import CartesianGeometry, StretchedCartesianGeometry
from dccrg_tpu.mapping import Mapping
from dccrg_tpu.topology import GridTopology

# reference midpoints, lookups per second (BASELINE.md)
REFERENCE = {
    "cartesian size": 1e8 / 1.315,
    "cartesian position": 1e8 / 4.245,
    "stretched size": 1e8 / 3.85,
    "stretched position": 1e8 / 9.675,
}


def measure(fn, ids, trials=5):
    """Best-of-N throughput: the machine is a shared single vCPU, so
    the minimum time is the signal, the rest is neighbor noise."""
    fn(ids)  # warm (allocator + native code paths)
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        fn(ids)
        best = min(best, time.perf_counter() - t0)
    return len(ids) / best


def main(n: int = 10_000_000) -> None:
    # same setup scale as the reference test: refined grid, random ids
    mapping = Mapping((32, 32, 32), maximum_refinement_level=5)
    topology = GridTopology((False, False, False))
    cart = CartesianGeometry(
        mapping, topology, start=(0.0, 0.0, 0.0),
        level_0_cell_length=(1.0, 2.0, 3.0),
    )
    coords = [np.cumsum(np.abs(np.random.default_rng(d).standard_normal(33)) + 0.1)
              for d in range(3)]
    stretched = StretchedCartesianGeometry(mapping, topology, coordinates=coords)

    rng = np.random.default_rng(0)
    lvl = rng.integers(0, 6, size=n)
    # random existing ids: level-major numbering
    ids = np.empty(n, dtype=np.uint64)
    base = 1
    counts = {}
    for l in range(6):
        counts[l] = (base, 32768 * 8**l)
        base += 32768 * 8**l
    for l in range(6):
        m = lvl == l
        lo, span = counts[l]
        ids[m] = lo + rng.integers(0, span, size=int(m.sum()))

    for name, geom in (("cartesian", cart), ("stretched", stretched)):
        for metric, fn in (("size", geom.get_length), ("position", geom.get_center)):
            rate = measure(fn, ids)
            key = f"{name} {metric}"
            print(json.dumps({
                "metric": f"geometry {key} lookups/sec",
                "value": rate,
                "unit": "lookups/s",
                "vs_baseline": rate / REFERENCE[key],
            }))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000)
