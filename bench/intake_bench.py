#!/usr/bin/env python
"""Streaming-intake saturation bench: the ISSUE "2x overload" proof
for the durable spool front door (``dccrg_tpu/intake.py``).

Three legs on one real-clock in-process (intake, scheduler) pair
over a shared spool + InMemoryKV:

- ``warmup``   — a couple of jobs to absorb the jit compile (not
  measured),
- ``calibrate``— ``--calibrate`` jobs drained to completion; the
  measured wall gives the steady drain rate ``intake_drain_per_sec``
  (higher is better; the fleet-side cost of going through the spool
  instead of the constructor),
- ``overload`` — submissions streamed at ``--overload`` (default 2x)
  the calibrated drain rate for ``--duration`` seconds while the
  scheduler serves tick-at-a-time. Under sustained overload the
  backpressure gate + journaled shed must keep the queue age bounded
  (``intake_p99_queue_age_seconds``, lower is better, from the
  telemetry queue-age histogram), flap at most once per EWMA window
  (``gate_transitions_per_window``), and lose or duplicate nothing:
  every submitted job must land in exactly one of
  {admitted+finished, shed/, quarantine/} — the bench ASSERTS the
  accounting and reports ``ok: false`` plus null trend metrics if it
  does not hold.

JSON rows go to stdout like the other bench emitters; on any failure
the summary still prints with null metric values so ``bench/trend.py``
skips (rather than crashes on) the round.

Run:  timeout -k 10 600 python bench/intake_bench.py [--duration 8]
      [--overload 2.0] [--calibrate 16]
"""

import argparse
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")


def _row(name, steps, seed):
    return {"name": name, "n": 8, "steps": steps,
            "checkpoint_every": 0, "seed": seed}


def _serve_until(sched, it, pred, deadline):
    """Tick the scheduler (which pumps the intake) until ``pred()``
    or the wall deadline; returns True when the predicate held."""
    while time.monotonic() < deadline:
        if pred():
            return True
        sched.run(max_ticks=sched.ticks + 1)
    return pred()


def run_bench(args):
    from dccrg_tpu import coord, intake, telemetry
    from dccrg_tpu.scheduler import FleetScheduler

    telemetry.registry().reset()
    tmp = tempfile.mkdtemp(prefix="intake_bench_")
    rows = []
    try:
        spool = str(Path(tmp) / "spool")
        it = intake.StreamIntake(
            spool, kv=coord.InMemoryKV(), rank=0, lease_s=2.0,
            window_s=1.0, age_bound_s=args.age_bound, poll_s=0.0,
            seed=args.seed)
        sched = FleetScheduler(str(Path(tmp) / "ck"), quantum=4,
                               intake=it)

        # -- warmup: absorb the compile outside the measured legs.
        # The gate is held open through warmup + calibration (the
        # spooled-up-front burst would spike the arrival EWMA and
        # gate-pause the drain we are trying to measure); the real
        # hysteresis band is restored for the overload leg.
        real_hi = it.hi_ratio
        it.hi_ratio = 1e9
        for i in range(2):
            intake.submit(spool, _row(f"w{i}", args.steps, i))
        sched.run()

        # -- calibrate: steady drain rate, jobs all spooled up front
        for i in range(args.calibrate):
            intake.submit(spool, _row(f"c{i:03d}", args.steps, i))
        t0 = time.monotonic()
        sched.run()
        cal_wall = time.monotonic() - t0
        it.hi_ratio = real_hi
        drain = args.calibrate / max(cal_wall, 1e-9)
        rows.append({"leg": "calibrate", "jobs": args.calibrate,
                     "wall_s": round(cal_wall, 4),
                     "drain_per_sec": round(drain, 3)})
        print(json.dumps(rows[-1]), flush=True)

        # -- overload: stream arrivals at --overload x the calibrated
        # drain rate, serving tick-at-a-time on the real clock
        rate = args.overload * drain
        total = max(8, min(int(rate * args.duration), 400))
        period = 1.0 / rate
        names = [f"o{i:04d}" for i in range(total)]
        base_tr = it.gate_transitions
        t0 = time.monotonic()
        nxt, i = t0, 0
        while i < len(names):
            now = time.monotonic()
            if now >= nxt:
                intake.submit(spool, _row(names[i], args.steps, i))
                nxt += period
                i += 1
            else:
                sched.run(max_ticks=sched.ticks + 1)
        shed_dir = Path(spool) / "shed"
        quar_dir = Path(spool) / "quarantine"

        def settled():
            done = set(sched.report)
            done.update(p.stem for p in shed_dir.glob("*.json"))
            done.update(p.stem for p in quar_dir.glob("*.json"))
            return all(n in done for n in names) and it.idle()

        ok = _serve_until(sched, it, settled,
                          time.monotonic() + args.duration + 60)
        wall = time.monotonic() - t0

        # exactly-once accounting: each overload job in exactly one
        # terminal place, and the admitted counter matches the set of
        # names the scheduler actually finished (no duplicates)
        finished = [n for n in names if n in sched.report]
        shed = [n for n in names
                if (shed_dir / f"{n}.json").exists()]
        quar = [n for n in names
                if (quar_dir / f"{n}.json").exists()]
        places = {}
        for bucket, got in (("finished", finished), ("shed", shed),
                            ("quarantined", quar)):
            for n in got:
                places.setdefault(n, []).append(bucket)
        lost = [n for n in names if n not in places]
        dupes = [n for n, b in places.items() if len(b) > 1]
        reg = telemetry.registry()
        overload_admits = (reg.counter_total(
            "dccrg_intake_admitted_total")
            - 2 - args.calibrate - it.reclaimed)
        ok = (ok and not lost and not dupes
              and int(overload_admits) == len(finished))

        hist = reg.histogram_total("dccrg_intake_queue_age_seconds")
        p99 = hist.quantile(0.99) if hist is not None else None
        transitions = it.gate_transitions - base_tr
        per_window = transitions / max(1.0, wall / it.window_s)
        rows.append({
            "leg": "overload", "submitted": total,
            "arrival_per_sec": round(rate, 3),
            "wall_s": round(wall, 4), "finished": len(finished),
            "shed": len(shed), "quarantined": len(quar),
            "lost": len(lost), "duplicated": len(dupes),
            "gate_transitions": transitions,
            "gate_transitions_per_window": round(per_window, 3),
            "ok": ok})
        print(json.dumps(rows[-1]), flush=True)

        summary = {
            "intake_drain_per_sec": (round(drain, 3) if ok else None),
            "intake_p99_queue_age_seconds": (
                round(p99, 4) if ok and p99 is not None else None),
            "gate_transitions_per_window": round(per_window, 3),
            "overload": args.overload, "submitted": total,
            "finished": len(finished), "shed": len(shed),
            "ok": ok,
            "note": ("sustained %.1fx overload; exactly-once "
                     "accounting %s" % (args.overload,
                                        "held" if ok else "FAILED")),
        }
    except Exception as e:  # null metrics: trend.py skips, not crashes
        summary = {"intake_drain_per_sec": None,
                   "intake_p99_queue_age_seconds": None,
                   "ok": False, "error": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps({"summary": summary}), flush=True)
    return 0 if summary.get("ok") else 1


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--calibrate", type=int, default=16,
                    help="jobs in the drain-rate calibration leg")
    ap.add_argument("--duration", type=float, default=8.0,
                    help="overload-leg submission window (seconds)")
    ap.add_argument("--overload", type=float, default=2.0,
                    help="arrival rate as a multiple of drain rate")
    ap.add_argument("--age-bound", type=float, default=4.0,
                    help="intake age bound driving shed (seconds)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from dccrg_tpu.resilience import safe_devices
    if safe_devices(timeout=120, retries=1, platform="cpu") is None:
        print(json.dumps({"summary": {
            "intake_drain_per_sec": None,
            "intake_p99_queue_age_seconds": None,
            "ok": False, "error": "device probe failed"}}))
        return 1
    return run_bench(args)


if __name__ == "__main__":
    sys.exit(main())
