#!/usr/bin/env python
"""A/B the overlapped fused step (DCCRG_OVERLAP) against the
sequential exchange -> kernel path on the GridAdvection workload.

The overlap launches the halo ppermutes before the bulk kernel and
redoes only the outer rows after the scatter (grid.py
compile_step_loop), mirroring the reference's
solve-inner-while-messages-fly split (dccrg.hpp:5046-5413,
tests/advection/2d.cpp:327-343). On accelerators the collective can
fly under the stencil; on the CPU backend collectives are memcpys so
the extra outer pass is pure overhead — this script measures both so
the default (_use_overlap: accelerators only) stays justified by data.

Usage: python bench/overlap_bench.py [--n 128] [--steps 10] [--cpu]
Prints one JSON line with both step rates.
"""

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run_leg(overlap, n, steps):
    os.environ["DCCRG_OVERLAP"] = "1" if overlap else "0"
    from dccrg_tpu.models.advection import GridAdvection

    solver = GridAdvection(n=n, nz=n)
    dt = 0.5 * solver.max_time_step()
    solver.run(1, dt)  # warmup/compile
    solver.checksum()
    t0 = time.perf_counter()
    solver.run(steps, dt)
    solver.checksum()
    elapsed = time.perf_counter() - t0
    return n * n * n * steps / elapsed, solver.l2_error()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual device mesh "
                    "via XLA_FLAGS still applies)")
    args = ap.parse_args()

    # device discovery through the hang-proof probe: a dead axon
    # tunnel fails fast instead of wedging the A/B
    from dccrg_tpu.resilience import safe_devices

    devices = safe_devices(timeout=120, retries=1,
                           platform="cpu" if args.cpu else None)

    ups = {}
    l2 = {}
    for mode in ("sequential", "overlap"):
        ups[mode], l2[mode] = run_leg(mode == "overlap", args.n, args.steps)
        print(f"{mode}: {ups[mode]:.4g} updates/s (l2 {l2[mode]:.3e})",
              file=sys.stderr)
    print(json.dumps({
        "metric": f"overlap A/B grid advection {args.n}^3",
        "platform": devices[0].platform,
        "n_devices": len(devices),
        "sequential_updates_per_sec": ups["sequential"],
        "overlap_updates_per_sec": ups["overlap"],
        "overlap_speedup": ups["overlap"] / ups["sequential"],
    }))


if __name__ == "__main__":
    main()
