"""Fleet serving throughput: batched many-grid multiplexing vs the
sequential one-grid-at-a-time loop (the ROADMAP "Fleet serving"
item's measuring stick).

For each concurrency level (default 1/8/32/100 jobs of ``--n``^3
cells, ``--steps`` steps each) the same job set runs twice:

- ``sequential`` — the pre-fleet baseline: one grid at a time through
  ``Grid.run_steps`` (one shared compile; each job re-inits the
  template grid), and
- ``fleet`` — one :class:`~dccrg_tpu.scheduler.FleetScheduler` batch:
  all jobs stacked along the batch axis into one jitted program.

Both passes produce per-job final-state digests; the bench ASSERTS
they match bitwise (it doubles as the end-to-end parity check), then
reports runs/s, cell-updates/s and mean per-job latency. Checkpoint
cadence is disabled in both passes so the number is pure stepping
throughput; ``--ckpt-every K`` turns the fleet data plane back on.

Run:  timeout -k 10 900 python bench/fleet_bench.py [--n 32]
      [--steps 20] [--jobs 1 8 32 100]

``--hosts N`` instead runs the ELASTIC multi-host leg: N in-process
rank-aware schedulers (shared InMemoryKV + checkpoint dir, real
clock, tight heartbeat/lease bounds) serve one job set; host 1 is
killed mid-serve (its tick driver stops — the in-process analogue of
the mp harness's real ``kill -9``) and the leg measures the recovery
wall: ``fleet_reclaim_seconds`` (kill -> the survivor's CAS takeover
of the first orphan) and ``fleet_kill_downtime_seconds`` (kill ->
the first reclaimed job's dispatch completes) — the two trend keys
``bench/trend.py`` tracks for the elastic control plane, with
bitwise solo-digest parity asserted for every job, victims included.

JSON rows go to stdout like the other bench emitters; the summary row
carries the runs/s table PERF.md quotes.
"""

import argparse
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402, F401
import numpy as np  # noqa: E402


def make_jobs(count, n, steps, ckpt_every):
    from dccrg_tpu.fleet import FleetJob

    return [FleetJob(f"b{i:04d}", length=(n, n, n), n_steps=steps,
                     params=(0.02 + 0.003 * (i % 7),), seed=i,
                     checkpoint_every=ckpt_every)
            for i in range(count)]


def run_sequential(count, n, steps, ckpt_every):
    """One grid at a time: a single template grid + compiled step
    loop, re-initialized per job (the strongest sequential baseline —
    a fresh Grid per job would also pay N plan builds + compiles)."""
    from dccrg_tpu import checkpoint as checkpoint_mod
    from dccrg_tpu.fleet import template_grid

    jobs = make_jobs(count, n, steps, ckpt_every)
    g = template_grid(jobs[0])
    # warm the compile outside the measured window (both passes get
    # this; compile amortizes to zero in steady serving)
    jobs[0].apply_init(g)
    g.run_steps(jobs[0].resolved_kernel(), jobs[0].fields_in,
                jobs[0].fields_out, 1,
                extra_args=(jnp.float32(jobs[0].params[0]),))
    digests = {}
    lat = []
    # symmetric accounting with run_fleet: its window starts AFTER
    # admission (init + scatter + step-0 keyframes), so the sequential
    # window likewise excludes each job's apply_init and measures
    # stepping + final digest only
    for j in jobs:
        j.apply_init(g)
        jax.block_until_ready(list(g.data.values()))
        t1 = time.perf_counter()
        g.run_steps(j.resolved_kernel(), j.fields_in, j.fields_out,
                    j.n_steps, extra_args=(jnp.float32(j.params[0]),))
        jax.block_until_ready(list(g.data.values()))
        digests[j.name] = checkpoint_mod.state_digest(g)
        lat.append(time.perf_counter() - t1)
    wall = sum(lat)
    return wall, digests, lat


def run_fleet(count, n, steps, ckpt_every, quantum):
    from dccrg_tpu.scheduler import FleetScheduler

    jobs = make_jobs(count, n, steps, ckpt_every)
    workdir = tempfile.mkdtemp(prefix="dccrg_fleet_bench_")
    try:
        sched = FleetScheduler(workdir, jobs, quantum=quantum)
        # warm the batched compile outside the measured window: a
        # throwaway batch with the same bucket key and capacity shares
        # the compiled program (the fleet program cache is keyed on
        # exactly that), so one dummy dispatch compiles it
        sched._admit_pending()
        from dccrg_tpu.fleet import GridBatch

        for bs in sched.buckets.values():
            for b in bs:
                dummy = GridBatch(jobs[0], b.capacity)
                dummy.step(np.ones(b.capacity, dtype=np.int32))
                dummy.finite_slots()
        t0 = time.perf_counter()
        report = sched.run()
        wall = time.perf_counter() - t0
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    assert all(r["status"] == "done" for r in report.values())
    return wall, {name: r["digest"] for name, r in report.items()}


def run_hosts(n_hosts, n, steps, quantum, heartbeat_s=0.1,
              lease_s=0.4):
    """The elastic multi-host leg: ``n_hosts`` in-process rank-aware
    schedulers over one shared KV + checkpoint dir; host 1 dies
    mid-serve and the survivors' lease-expiry reclaim is timed."""
    from dccrg_tpu import coord, telemetry
    from dccrg_tpu.fleet import run_solo
    from dccrg_tpu.scheduler import FleetScheduler

    count = max(2, 2 * n_hosts)
    kv = coord.InMemoryKV()
    workdir = tempfile.mkdtemp(prefix="dccrg_fleet_hosts_")
    refs = {j.name: run_solo(j)
            for j in make_jobs(count, n, steps, 4)}
    try:
        scheds = []
        for rank in range(n_hosts):
            m = coord.Membership(rank, n_hosts, kv=kv,
                                 heartbeat_s=heartbeat_s,
                                 lease_s=lease_s, clock=time.monotonic)
            scheds.append(FleetScheduler(
                workdir, make_jobs(count, n, steps, 4),
                quantum=quantum or 4, membership=m))
        names = [f"b{i:04d}" for i in range(count)]
        reg = telemetry.registry()
        base_reclaims = reg.counter_total("dccrg_fleet_reclaims_total")

        def tick(s):
            s.run(max_ticks=s.ticks + 1)

        def _disp_total(name):
            h = reg.histogram("dccrg_fleet_quantum_seconds", job=name)
            return 0 if h is None else h.total

        victim = scheds[1] if n_hosts > 1 else None
        live = list(scheds)
        orphans, disp_base = [], {}
        t_kill = t_reclaim = t_first_dispatch = None
        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
            for s in live:
                tick(s)
            done = sum(1 for nm in names if nm in scheds[0].report)
            if victim is not None and t_kill is None \
                    and victim.leases.owned \
                    and any(j.steps_done > 0
                            for _b, _s2, j in victim.active_jobs()):
                # the victim is mid-serve with real progress: kill it
                # (ticks and heartbeats both cease — the in-process
                # analogue of the mp harness's real kill -9)
                t_kill = time.monotonic()
                victim.membership.stop_auto()
                orphans = sorted(victim.leases.owned)
                disp_base = {nm: _disp_total(nm) for nm in orphans}
                live = [s for s in scheds if s is not victim]
            if t_kill is not None and t_reclaim is None \
                    and reg.counter_total("dccrg_fleet_reclaims_total") \
                    > base_reclaims:
                t_reclaim = time.monotonic()
            if t_reclaim is not None and t_first_dispatch is None \
                    and any(_disp_total(nm) > disp_base[nm]
                            for nm in orphans):
                # a survivor finished a dispatch that ADVANCED a
                # reclaimed job: serving resumed
                t_first_dispatch = time.monotonic()
            if done == count and (victim is None
                                  or t_first_dispatch is not None):
                break
        report = {}
        for s in live:
            for nm, row in s.report.items():
                if not row.get("remote"):
                    report[nm] = row
        assert sorted(report) == names, sorted(report)
        for nm, row in report.items():
            assert row["status"] == "done" and row["digest"] == refs[nm], nm
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    row = {
        "hosts": n_hosts, "jobs": count, "cells_per_job": n ** 3,
        "steps": steps,
        "heartbeat_s": heartbeat_s, "lease_s": lease_s,
        "fleet_reclaim_seconds": (
            None if t_kill is None or t_reclaim is None
            else round(t_reclaim - t_kill, 4)),
        "fleet_kill_downtime_seconds": (
            None if t_kill is None or t_first_dispatch is None
            else round(t_first_dispatch - t_kill, 4)),
        "orphans_reclaimed": len(orphans) if t_kill is not None else 0,
        "bitwise_parity": True,
    }
    print(json.dumps(row), flush=True)
    print(json.dumps({"summary": row}), flush=True)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=32,
                    help="grid edge length per job (n^3 cells)")
    ap.add_argument("--steps", type=int, default=20,
                    help="steps per job")
    ap.add_argument("--jobs", type=int, nargs="+",
                    default=(1, 8, 32, 100),
                    help="concurrency levels to measure")
    ap.add_argument("--quantum", type=int, default=None)
    ap.add_argument("--ckpt-every", type=int, default=0,
                    help="fleet checkpoint cadence (0 = pure stepping)")
    ap.add_argument("--hosts", type=int, default=None, metavar="N",
                    help="elastic multi-host leg: N in-process "
                         "rank-aware schedulers, host 1 killed "
                         "mid-serve, reclaim latency measured")
    args = ap.parse_args()

    # hang-proof backend probe before any jax work (like the other
    # benches: a wedged accelerator tunnel survives SIGTERM)
    from dccrg_tpu.resilience import safe_devices

    safe_devices(timeout=120, retries=1, platform="cpu")

    if args.hosts is not None:
        return run_hosts(args.hosts, min(args.n, 12), args.steps,
                         args.quantum)

    cells = args.n ** 3
    rows = []
    for count in args.jobs:
        seq_wall, seq_digests, seq_lat = run_sequential(
            count, args.n, args.steps, args.ckpt_every)
        flt_wall, flt_digests = run_fleet(
            count, args.n, args.steps, args.ckpt_every,
            args.quantum)
        assert flt_digests == seq_digests, \
            "fleet digests differ from the sequential baseline"
        updates = count * cells * args.steps
        row = {
            "jobs": count, "cells_per_job": cells, "steps": args.steps,
            "seq_wall_s": round(seq_wall, 4),
            "fleet_wall_s": round(flt_wall, 4),
            "seq_runs_per_s": round(count / seq_wall, 3),
            "fleet_runs_per_s": round(count / flt_wall, 3),
            "seq_updates_per_s": round(updates / seq_wall),
            "fleet_updates_per_s": round(updates / flt_wall),
            "seq_job_latency_s": round(sum(seq_lat) / len(seq_lat), 4),
            "fleet_job_latency_s": round(flt_wall / count, 4),
            "speedup": round(seq_wall / flt_wall, 2),
            "bitwise_parity": True,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    best = max(rows, key=lambda r: r["speedup"])
    summary = {
        "n": args.n, "steps": args.steps,
        "max_jobs": max(r["jobs"] for r in rows),
        "best_speedup": best["speedup"],
        "best_speedup_jobs": best["jobs"],
        "fleet_runs_per_s_at_max": rows[-1]["fleet_runs_per_s"],
        "seq_runs_per_s_at_max": rows[-1]["seq_runs_per_s"],
    }
    print(json.dumps({"summary": summary}), flush=True)
    return summary


if __name__ == "__main__":
    main()
