#!/bin/bash
# One-shot chip measurement battery: run the moment the TPU tunnel
# answers (see bench/tpu_poller.sh -> /tmp/tpu_up). Captures every
# staged measurement in priority order so a short window still gets
# the headline numbers first. Outputs land in bench/chip_results/.
set -u
cd "$(dirname "$0")/.."
out=bench/chip_results
mkdir -p "$out"
ts=$(date +%s)

# A preempted session (the tunnel window closes with a SIGTERM) must
# leave no stale lock/temp files: kill the in-flight measurement,
# drop the running marker AND the poller's one-shot latch so the next
# tunnel contact fires a fresh session, and record the preemption in
# the log. Finished measurement outputs are kept — partial data from
# a short window is the point of the priority ordering below.
lock="$out/.chip_session_running_$ts"
CHILD=""
# the lock is an operator-visible "session in flight" marker; the
# EXIT trap (which also fires after the TERM/INT one) removes it on
# EVERY exit path — error, preemption or completion — so it can
# never go stale
trap 'rm -f "$lock"' EXIT
trap 'echo "PREEMPTED (TERM/INT): session cut short" | tee -a "$out/log_$ts.txt"; [ -n "$CHILD" ] && kill "$CHILD" 2>/dev/null; rm -f /tmp/tpu_session_started; exit 143' TERM INT
touch "$lock"

run() { # name, timeout_s, cmd...
  local name=$1 t=$2; shift 2
  echo "=== $name ($(date +%T)) ===" | tee -a "$out/log_$ts.txt"
  # background + `wait` so the TERM trap fires mid-measurement too
  # (bash defers traps while a foreground command runs)
  timeout -k 10 "$t" "$@" >"$out/${name}_$ts.out" 2>&1 &
  CHILD=$!
  wait "$CHILD"
  local rc=$?
  CHILD=""
  echo "rc=$rc $name" | tee -a "$out/log_$ts.txt"
  tail -3 "$out/${name}_$ts.out" | tee -a "$out/log_$ts.txt"
}

# 1. the headline: 512^3 grid path + both A/Bs + pallas bound + bf16
#    + the roll-plan bulk-executor leg (bench.py runs DCCRG_BULK=pallas
#    as its own leg with L2 parity asserted against the XLA roll path)
run bench_main 3600 python bench.py
# 1b. bulk-executor A/B as the HEADLINE mode (native Pallas, plus the
#     temporally-blocked depth-4 point) — the >=10x grid-path target's
#     direct measurement; compare grid_path_updates_per_sec across the
#     bench_main / bulk_spp{1,4} outputs
run bench_bulk_spp1 3600 env BENCH_SKIP_AB=1 BENCH_SKIP_BF16=1 \
    BENCH_SKIP_BULK=1 DCCRG_BULK=pallas python bench.py
run bench_bulk_spp4 3600 env BENCH_SKIP_AB=1 BENCH_SKIP_BF16=1 \
    BENCH_SKIP_BULK=1 DCCRG_BULK=pallas DCCRG_BULK_SPP=4 python bench.py
# 1c. bf16 end-to-end state through the bulk executor (narrow HBM
#     residency x temporal blocking — the compounding legs)
run bench_bulk_bf16 1800 env BENCH_SKIP_AB=1 BENCH_SKIP_BF16=1 \
    BENCH_SKIP_BULK=1 DCCRG_BULK=pallas BENCH_GRID_DTYPE=bfloat16 \
    python bench.py
# 2. pallas bound, narrow storage
run bench_pallas_bf16 1800 env BENCH_SKIP_AB=1 BENCH_SKIP_BF16=1 \
    BENCH_PALLAS_DTYPE=bfloat16 python bench.py
# 3. poisson kernel VMEM fit + rates
run poisson_256 1200 python bench/poisson_bench.py --n 256
# 4. native pallas/poisson kernel tests on the chip
run tpu_tests 1800 env DCCRG_TEST_TPU=1 python -m pytest tests/ -q
# 5. overlap A/B on the chip backend (single chip: mesh of 1 device —
#    records the no-exchange baseline sanity)
run overlap_ab 900 python bench/overlap_bench.py --n 128
echo "chip session complete: $out (ts $ts)" | tee -a "$out/log_$ts.txt"
