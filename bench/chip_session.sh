#!/bin/bash
# One-shot chip measurement battery: run the moment the TPU tunnel
# answers (see bench/tpu_poller.sh -> /tmp/tpu_up). Captures every
# staged measurement in priority order so a short window still gets
# the headline numbers first. Outputs land in bench/chip_results/.
set -u
cd "$(dirname "$0")/.."
out=bench/chip_results
mkdir -p "$out"
ts=$(date +%s)

run() { # name, timeout_s, cmd...
  local name=$1 t=$2; shift 2
  echo "=== $name ($(date +%T)) ===" | tee -a "$out/log_$ts.txt"
  timeout -k 10 "$t" "$@" >"$out/${name}_$ts.out" 2>&1
  echo "rc=$? $name" | tee -a "$out/log_$ts.txt"
  tail -3 "$out/${name}_$ts.out" | tee -a "$out/log_$ts.txt"
}

# 1. the headline: 512^3 grid path + both A/Bs + pallas bound + bf16
run bench_main 3600 python bench.py
# 2. pallas bound, narrow storage
run bench_pallas_bf16 1800 env BENCH_SKIP_AB=1 BENCH_SKIP_BF16=1 \
    BENCH_PALLAS_DTYPE=bfloat16 python bench.py
# 3. poisson kernel VMEM fit + rates
run poisson_256 1200 python bench/poisson_bench.py --n 256
# 4. native pallas/poisson kernel tests on the chip
run tpu_tests 1800 env DCCRG_TEST_TPU=1 python -m pytest tests/ -q
# 5. overlap A/B on the chip backend (single chip: mesh of 1 device —
#    records the no-exchange baseline sanity)
run overlap_ab 900 python bench/overlap_bench.py --n 128
echo "chip session complete: $out (ts $ts)" | tee -a "$out/log_$ts.txt"
