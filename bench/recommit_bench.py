"""AMR plan re-commit cost, per phase (the ROADMAP "Hybrid re-commit
cost at 192^3" item's measuring stick).

Each size refines a z-slab (1/64 of the level-0 cells) and commits,
then refines a second slab and commits again — the *reuse* epoch the
epoch-to-epoch stream cache and the plan arena accelerate — and
finally runs two more alternating unrefine/refine commits so the
steady-state adapt loop (warm arena, stable sticky-cap shapes) is on
record too.  ``--no-reuse`` clears the stream cache before every
re-commit, isolating the reuse machinery's contribution.  Per-phase
timings come from hybrid.py's phase marks via ``_PHASE_SINK`` (no
stdout parsing).

Run:  timeout -k 10 1800 python bench/recommit_bench.py [--max 128]
      (192^3 takes minutes on a 1-core host; opt in with --max 192)

JSON rows go to stdout like the other bench emitters.
"""

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import dccrg_tpu as dt  # noqa: E402
from dccrg_tpu import hybrid  # noqa: E402


def _phase_groups(records):
    """Collapse the raw (label, seconds) marks into the four coarse
    recommit phases."""
    groups = {"classify": 0.0, "hard_streams": 0.0, "easy_far_tables": 0.0,
              "hard_tables": 0.0, "layout_other": 0.0}
    for label, secs in records:
        if label.startswith("classify"):
            groups["classify"] += secs
        elif label.startswith("hard streams"):
            groups["hard_streams"] += secs
        elif "far" in label or "easy" in label:
            groups["easy_far_tables"] += secs
        elif "hard" in label:
            groups["hard_tables"] += secs
        else:
            groups["layout_other"] += secs
    return {k: round(v, 3) for k, v in groups.items()}


def _commit(g, reuse):
    if not reuse:
        # fingerprint mismatch -> full rebuild (streams recomputed);
        # the arena still serves warm buffers, isolating stream reuse
        g._hybrid_reuse = {}
    sink = []
    hybrid._PHASE_SINK = sink
    try:
        t0 = time.perf_counter()
        g.stop_refining()
        total = time.perf_counter() - t0
    finally:
        hybrid._PHASE_SINK = None
    return total, _phase_groups(sink)


def run_size(n, reuse=True):
    g = (dt.Grid(cell_data={"density": jnp.float32})
         .set_initial_length((n, n, n))
         .set_maximum_refinement_level(1)
         .set_neighborhood_length(1)
         .initialize())
    n0 = np.uint64(n) ** 3
    nref = int(n0) // 64
    rows = []

    def emit(epoch, total, phases):
        row = {
            "size": f"{n}^3", "epoch": epoch, "reuse": reuse,
            "cells": len(g.plan.cells), "total_s": round(total, 2),
            "phases": phases,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    cells = g.plan.cells
    for c in cells[:nref]:
        g.refine_completely(c)
    emit("first", *_commit(g, reuse))

    cells = g.plan.cells
    lvl0 = cells[cells <= n0]
    for c in lvl0[-nref:]:
        g.refine_completely(c)
    emit("recommit", *_commit(g, reuse))

    # steady-state adapt loop: alternate a smaller unrefine/refine so
    # the sticky-cap shapes (and with them the arena buffers) settle
    for it in range(2):
        cells = g.plan.cells
        lvl1 = cells[cells > n0]
        for c in lvl1[:nref // 2:8]:
            g.unrefine_completely(int(c))
        emit(f"steady{it}a", *_commit(g, reuse))
        cells = g.plan.cells
        lvl0 = cells[cells <= n0]
        for c in lvl0[:nref // 16]:
            g.refine_completely(int(c))
        emit(f"steady{it}b", *_commit(g, reuse))
    arena = getattr(g, "_plan_arena", None)
    if arena is not None:
        print(json.dumps({"size": f"{n}^3", "arena": arena.stats()}),
              flush=True)
    del g
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max", type=int, default=128,
                    help="largest edge length (64/128/192)")
    ap.add_argument("--no-reuse", action="store_true",
                    help="clear the stream-reuse cache before every "
                         "commit (isolates the reuse win)")
    args = ap.parse_args()

    # hang-proof backend probe before any jax work (like the other
    # benches: a wedged accelerator tunnel survives SIGTERM)
    from dccrg_tpu.resilience import safe_devices

    safe_devices(timeout=120, retries=1, platform="cpu")

    results = []
    for n in (64, 128, 192):
        if n > args.max:
            continue
        results.extend(run_size(n, reuse=not args.no_reuse))
    return results


if __name__ == "__main__":
    main()
