"""AMR plan re-commit cost, per phase (the ROADMAP "Hybrid re-commit
cost at 192^3" item's measuring stick).

Each size refines a z-slab (1/64 of the level-0 cells) and commits,
then refines a second slab and commits again — the *reuse* epoch the
epoch-to-epoch stream cache and the plan arena accelerate — and
finally runs two more alternating unrefine/refine commits so the
steady-state adapt loop (warm arena, stable sticky-cap shapes) is on
record too.  ``--no-reuse`` clears the stream cache before every
re-commit, isolating the reuse machinery's contribution.  Per-phase
timings come from hybrid.py's phase marks via ``_PHASE_SINK`` (no
stdout parsing).

``--overlap`` runs the zero-stall leg instead: the same adapt epochs
with a serving loop (small run_steps quanta) around them, measuring
**step-loop stall seconds** — how long the loop is actually blocked —
synchronous vs ``DCCRG_BG_RECOMMIT=1`` background builds. In sync
mode the stall is the whole ``stop_refining`` wall; in background
mode it is the (resolve + submit) wall plus the step-boundary swap
install, read from the ``dccrg_recommit_stall_seconds`` histogram the
swap point feeds. Plan fingerprints are asserted bitwise-identical
between the two modes at every epoch, and the bg leg also reports the
steps it served while the build ran.

Run:  timeout -k 10 1800 python bench/recommit_bench.py [--max 128]
      (192^3 takes minutes on a 1-core host; opt in with --max 192)

JSON rows go to stdout like the other bench emitters; the --overlap
summary keys (``recommit<N>_stall_sync_seconds`` /
``_stall_bg_seconds``) follow the bench/trend.py lower-is-better
naming so checked-in rounds trend automatically.
"""

import argparse
import hashlib
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import dccrg_tpu as dt  # noqa: E402
from dccrg_tpu import hybrid  # noqa: E402


def _phase_groups(records):
    """Collapse the raw (label, seconds) marks into the four coarse
    recommit phases."""
    groups = {"classify": 0.0, "hard_streams": 0.0, "easy_far_tables": 0.0,
              "hard_tables": 0.0, "layout_other": 0.0}
    for label, secs in records:
        if label.startswith("classify"):
            groups["classify"] += secs
        elif label.startswith("hard streams"):
            groups["hard_streams"] += secs
        elif "far" in label or "easy" in label:
            groups["easy_far_tables"] += secs
        elif "hard" in label:
            groups["hard_tables"] += secs
        else:
            groups["layout_other"] += secs
    return {k: round(v, 3) for k, v in groups.items()}


def _commit(g, reuse):
    if not reuse:
        # fingerprint mismatch -> full rebuild (streams recomputed);
        # the arena still serves warm buffers, isolating stream reuse
        g._hybrid_reuse = {}
    sink = []
    hybrid._PHASE_SINK = sink
    try:
        t0 = time.perf_counter()
        g.stop_refining()
        total = time.perf_counter() - t0
    finally:
        hybrid._PHASE_SINK = None
    return total, _phase_groups(sink)


def run_size(n, reuse=True):
    g = (dt.Grid(cell_data={"density": jnp.float32})
         .set_initial_length((n, n, n))
         .set_maximum_refinement_level(1)
         .set_neighborhood_length(1)
         .initialize())
    n0 = np.uint64(n) ** 3
    nref = int(n0) // 64
    rows = []

    def emit(epoch, total, phases):
        row = {
            "size": f"{n}^3", "epoch": epoch, "reuse": reuse,
            "cells": len(g.plan.cells), "total_s": round(total, 2),
            "phases": phases,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    cells = g.plan.cells
    for c in cells[:nref]:
        g.refine_completely(c)
    emit("first", *_commit(g, reuse))

    cells = g.plan.cells
    lvl0 = cells[cells <= n0]
    for c in lvl0[-nref:]:
        g.refine_completely(c)
    emit("recommit", *_commit(g, reuse))

    # steady-state adapt loop: alternate a smaller unrefine/refine so
    # the sticky-cap shapes (and with them the arena buffers) settle
    for it in range(2):
        cells = g.plan.cells
        lvl1 = cells[cells > n0]
        for c in lvl1[:nref // 2:8]:
            g.unrefine_completely(int(c))
        emit(f"steady{it}a", *_commit(g, reuse))
        cells = g.plan.cells
        lvl0 = cells[cells <= n0]
        for c in lvl0[:nref // 16]:
            g.refine_completely(int(c))
        emit(f"steady{it}b", *_commit(g, reuse))
    arena = getattr(g, "_plan_arena", None)
    if arena is not None:
        print(json.dumps({"size": f"{n}^3", "arena": arena.stats()}),
              flush=True)
    del g
    return rows


# ---------------------------------------------------------------------
# the --overlap leg: step-loop stall seconds, sync vs background
# ---------------------------------------------------------------------

def _plan_fp(g):
    """Compact bitwise plan fingerprint (layout + materialized hood
    tables; the lazy to-tables stay lazy in BOTH modes, so they are
    excluded identically)."""
    h = hashlib.sha256()
    p = g.plan
    h.update(np.ascontiguousarray(p.cells).tobytes())
    h.update(np.ascontiguousarray(p.owner).tobytes())
    h.update(str((p.L, p.R)).encode())
    h.update(np.ascontiguousarray(p.row_of_pos).tobytes())
    for hood in p.hoods.values():
        h.update(np.ascontiguousarray(hood.nbr_rows).tobytes())
        h.update(np.ascontiguousarray(hood.nbr_mask).tobytes())
        for t in (hood.scale_rows, hood.hard_rows, hood.hard_nbr_rows,
                  hood.hard_offs, hood.hard_mask):
            if t is not None:
                h.update(np.ascontiguousarray(t).tobytes())
    return h.hexdigest()


def _diffuse(cell, nbr, offs, mask, *extra):
    s = jnp.sum(jnp.where(mask, nbr["density"] - cell["density"][:, None],
                          0.0), axis=1)
    return {"density": cell["density"] + 0.01 * s}


def _swap_stall_total():
    from dccrg_tpu import telemetry

    tot = 0.0
    for (nm, _lab), h in telemetry.registry().histograms.items():
        if nm == "dccrg_recommit_stall_seconds":
            tot += h.sum_seconds
    return tot


def run_overlap_size(n, quantum=2):
    """One size's sync-vs-background stall comparison. Both modes run
    the identical adapt schedule and serve the identical total step
    count; the difference is WHERE the build cost lands."""
    n0 = int(np.uint64(n) ** 3)
    nref = n0 // 64

    def serve(bg):
        os.environ["DCCRG_BG_RECOMMIT"] = "1" if bg else "0"
        g = (dt.Grid(cell_data={"density": jnp.float32})
             .set_initial_length((n, n, n))
             .set_maximum_refinement_level(1)
             .set_neighborhood_length(1)
             .initialize())
        cells = g.plan.cells
        g.set("density", cells, np.arange(len(cells)) % 97.0)
        g.run_steps(_diffuse, ["density"], ["density"], quantum)  # warm

        def quantum_step():
            # block per quantum: a real serving loop consumes each
            # quantum's results, and unconsumed async dispatches would
            # otherwise pile up and bill their compute to whatever
            # blocks next (the swap), corrupting the stall accounting
            g.run_steps(_diffuse, ["density"], ["density"], quantum)
            jax.block_until_ready(g.data["density"])

        epochs = []

        def adapt_epoch(label, schedule):
            schedule()
            stall0 = _swap_stall_total()
            t0 = time.perf_counter()
            g.stop_refining()
            adapt_wall = time.perf_counter() - t0
            served = 0
            if bg:
                # the serving loop: keep stepping on the live plan;
                # run_steps installs the finished plan at a boundary
                while g.bg_pending():
                    quantum_step()
                    served += quantum
                stall = adapt_wall + (_swap_stall_total() - stall0)
            else:
                stall = adapt_wall
            # equal total service in both modes: the sync leg serves
            # its quanta after the commit instead of during it
            while served < 8 * quantum:
                quantum_step()
                served += quantum
            epochs.append({"epoch": label,
                           "stall_s": round(stall, 3),
                           "adapt_call_s": round(adapt_wall, 3),
                           "fp": _plan_fp(g)})

        def first():
            for c in g.plan.cells[:nref]:
                g.refine_completely(c)

        def second():
            cs = g.plan.cells
            lvl0 = cs[cs <= np.uint64(n0)]
            for c in lvl0[-nref:]:
                g.refine_completely(int(c))

        def third():
            cs = g.plan.cells
            lvl1 = cs[cs > np.uint64(n0)]
            for c in lvl1[:nref // 2:8]:
                g.unrefine_completely(int(c))

        adapt_epoch("first", first)
        adapt_epoch("steady-refine", second)
        adapt_epoch("steady-unrefine", third)
        del g
        return epochs

    sync = serve(bg=False)
    bg = serve(bg=True)
    os.environ.pop("DCCRG_BG_RECOMMIT", None)
    rows = []
    for s, b in zip(sync, bg):
        assert s["fp"] == b["fp"], (
            f"plan fingerprint diverged at {s['epoch']} — background "
            "builds must be bitwise identical to synchronous ones")
        row = {"size": f"{n}^3", "epoch": s["epoch"],
               "stall_sync_s": s["stall_s"], "stall_bg_s": b["stall_s"],
               "stall_ratio": round(s["stall_s"]
                                    / max(b["stall_s"], 1e-9), 2),
               "fp_match": True}
        rows.append(row)
        print(json.dumps(row), flush=True)
    # steady-state summary (trend.py keys): the LAST two epochs are
    # the warm adapt loop the ROADMAP item is about
    steady_sync = sum(r["stall_sync_s"] for r in rows[1:])
    steady_bg = sum(r["stall_bg_s"] for r in rows[1:])
    summary = {
        f"recommit{n}_stall_sync_seconds": round(steady_sync, 3),
        f"recommit{n}_stall_bg_seconds": round(steady_bg, 3),
    }
    print(json.dumps({"size": f"{n}^3", "overlap_summary": summary}),
          flush=True)
    return rows, summary


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max", type=int, default=128,
                    help="largest edge length (64/128/192)")
    ap.add_argument("--no-reuse", action="store_true",
                    help="clear the stream-reuse cache before every "
                         "commit (isolates the reuse win)")
    ap.add_argument("--overlap", action="store_true",
                    help="measure step-loop stall seconds sync vs "
                         "DCCRG_BG_RECOMMIT=1 (bitwise plan parity "
                         "asserted per epoch)")
    args = ap.parse_args()

    # hang-proof backend probe before any jax work (like the other
    # benches: a wedged accelerator tunnel survives SIGTERM)
    from dccrg_tpu.resilience import safe_devices

    safe_devices(timeout=120, retries=1, platform="cpu")

    results = []
    for n in (64, 128, 192):
        if n > args.max:
            continue
        if args.overlap:
            results.append(run_overlap_size(n))
        else:
            results.extend(run_size(n, reuse=not args.no_reuse))
    return results


if __name__ == "__main__":
    main()
