// Native CPU baseline for the advection benchmark: the same math as
// the reference's tests/advection hot loop (solve.hpp:44-279) on a
// uniform grid — first-order upwind fluxes with face-averaged
// velocities — written as a plain C++ triple loop at -O3. Measures
// single-core cell-updates/sec; bench.py scales it by a nominal node
// core count to estimate the reference's single-node MPI throughput.
//
// Usage: baseline_advection N NZ STEPS
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <vector>

int main(int argc, char** argv) {
    const int n = argc > 1 ? std::atoi(argv[1]) : 128;
    const int nz = argc > 2 ? std::atoi(argv[2]) : 16;
    const int steps = argc > 3 ? std::atoi(argv[3]) : 5;
    const double dx = 1.0 / n;
    const size_t total = (size_t)n * n * nz;

    std::vector<float> rho(total), vx(total), vy(total), out(total);
    auto idx = [&](int i, int j, int k) { return ((size_t)k * n + j) * n + i; };
    for (int k = 0; k < nz; k++)
        for (int j = 0; j < n; j++)
            for (int i = 0; i < n; i++) {
                const double x = (i + 0.5) * dx, y = (j + 0.5) * dx;
                const double r0 = std::sqrt((x - 0.25) * (x - 0.25) + (y - 0.5) * (y - 0.5));
                const double r = std::min(r0, 0.15) / 0.15;
                rho[idx(i, j, k)] = 0.25f * (1.0f + std::cos(M_PI * r));
                vx[idx(i, j, k)] = 0.5f - y;
                vy[idx(i, j, k)] = x - 0.5f;
            }

    const float dt = 0.5f * dx / 0.71f;  // CFL vs max |v| ~ sqrt(2)/2
    const auto t0 = std::chrono::steady_clock::now();
    for (int s = 0; s < steps; s++) {
        for (int k = 0; k < nz; k++)
            for (int j = 0; j < n; j++)
                for (int i = 0; i < n; i++) {
                    const size_t c = idx(i, j, k);
                    float d = rho[c];
                    // x faces (periodic)
                    const int im = i == 0 ? n - 1 : i - 1, ip = i == n - 1 ? 0 : i + 1;
                    const int jm = j == 0 ? n - 1 : j - 1, jp = j == n - 1 ? 0 : j + 1;
                    const size_t cxm = idx(im, j, k), cxp = idx(ip, j, k);
                    const size_t cym = idx(i, jm, k), cyp = idx(i, jp, k);
                    float vf_hi = 0.5f * (vx[c] + vx[cxp]);
                    float vf_lo = 0.5f * (vx[cxm] + vx[c]);
                    float fx_hi = vf_hi * (vf_hi >= 0 ? rho[c] : rho[cxp]);
                    float fx_lo = vf_lo * (vf_lo >= 0 ? rho[cxm] : rho[c]);
                    d += (fx_lo - fx_hi) * dt / dx;
                    vf_hi = 0.5f * (vy[c] + vy[cyp]);
                    vf_lo = 0.5f * (vy[cym] + vy[c]);
                    float fy_hi = vf_hi * (vf_hi >= 0 ? rho[c] : rho[cyp]);
                    float fy_lo = vf_lo * (vf_lo >= 0 ? rho[cym] : rho[c]);
                    d += (fy_lo - fy_hi) * dt / dx;
                    out[c] = d;
                }
        std::swap(rho, out);
    }
    const auto t1 = std::chrono::steady_clock::now();
    const double secs = std::chrono::duration<double>(t1 - t0).count();
    std::printf("%.6g\n", (double)total * steps / secs);
    // keep the result live
    volatile float sink = rho[total / 2];
    (void)sink;
    return 0;
}
