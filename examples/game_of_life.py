#!/usr/bin/env python
"""Game of life with split-phase halo updates (reference
examples/game_of_life.cpp): start the remote-copy update, do the work
that doesn't need fresh ghosts, finish receives before reading
neighbors, finish sends before overwriting local state — the
reference's solve-inner-while-messages-fly structure, expressed
through the same four-call API. (On device, the fused
``Grid.run_steps`` + ``DCCRG_OVERLAP`` path performs this overlap
inside one XLA program; this example demonstrates the HOST-side
split-phase parity API.)

The board is verified against a pure-numpy life simulation every turn,
and per-turn speed statistics are printed like the reference's.

Run (defaults to a virtual 8-device CPU mesh):
    python examples/game_of_life.py
"""

import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_plat = os.environ.get("DCCRG_EXAMPLE_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _plat
_flags = os.environ.get("XLA_FLAGS", "")
if _plat == "cpu" and "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", _plat)

import numpy as np
import jax.numpy as jnp

from dccrg_tpu.grid import Grid

N = 60
TURNS = 20


def numpy_life_step(board):
    """Zero-boundary (non-periodic) life step, the oracle."""
    nbrs = np.zeros_like(board, dtype=np.int64)
    for dx in (-1, 0, 1):
        for dy in (-1, 0, 1):
            if dx == dy == 0:
                continue
            sh = np.zeros_like(board, dtype=np.int64)
            xs = slice(max(dx, 0), board.shape[0] + min(dx, 0))
            xd = slice(max(-dx, 0), board.shape[0] + min(-dx, 0))
            ys = slice(max(dy, 0), board.shape[1] + min(dy, 0))
            yd = slice(max(-dy, 0), board.shape[1] + min(-dy, 0))
            sh[xd, yd] = board[xs, ys]
            nbrs += sh
    return (nbrs == 3) | (board.astype(bool) & (nbrs == 2))


def count_kernel(cell, nbr, offs, mask):
    return {"nbrs": jnp.sum(jnp.where(mask, nbr["alive"], 0), axis=1)}


def rules_kernel(cell, nbr, offs, mask):
    nb = cell["nbrs"]
    alive = (nb == 3) | ((cell["alive"] > 0) & (nb == 2))
    return {"alive": alive.astype(jnp.int32)}


def main() -> None:
    grid = (
        Grid(cell_data={"alive": jnp.int32, "nbrs": jnp.int32})
        .set_initial_length((N, N, 1))
        .set_neighborhood_length(1)
        .set_maximum_refinement_level(0)
        .initialize(partition="block")
    )
    grid.balance_load()

    rng = np.random.default_rng(42)
    board = (rng.random((N, N)) < 0.3).astype(np.int32)
    cells = grid.plan.cells  # ids 1..N*N in x-fastest order
    grid.set("alive", cells, board.reshape(-1, order="F").astype(np.int32))

    n_inner = len(grid.inner_cells())
    n_outer = len(grid.outer_cells())
    t0 = time.perf_counter()
    for turn in range(TURNS):
        # start updating cell data from other devices; the work that
        # only needs local rows could proceed here (the reference
        # computes inner cells' neighbor counts now)
        grid.start_remote_neighbor_copy_updates(fields=["alive"])

        # fresh ghosts are needed to count neighbors: finish receives
        grid.wait_remote_neighbor_copy_update_receives()
        grid.apply_stencil(count_kernel, ["alive"], ["nbrs"])

        # local state may only change once sends are done
        grid.wait_remote_neighbor_copy_update_sends()
        grid.apply_stencil(rules_kernel, ["alive", "nbrs"], ["alive"])

        board = numpy_life_step(board).astype(np.int32)
        got = np.asarray(grid.get("alive", cells)).reshape((N, N), order="F")
        assert np.array_equal(got, board), f"turn {turn}: board diverged"
    elapsed = time.perf_counter() - t0

    total = TURNS * (n_inner + n_outer)
    print(f"inner cells {n_inner}, outer cells {n_outer}")
    print(f"{TURNS} turns verified against the numpy oracle")
    print(f"speed: {total / elapsed:.3g} cells/s ({elapsed:.2f}s)")
    print("PASSED")


if __name__ == "__main__":
    main()
