#!/usr/bin/env python
"""Adaptive advection: the reference advection test's full loop
(tests/advection/2d.cpp) — upwind solve, adapt every 4 steps, balance
every 10 — with VTK snapshots of the refined grid.

Run (defaults to a virtual 8-device CPU mesh):
    python examples/amr_advection.py [steps] [outdir]
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# examples default to the virtual 8-device CPU mesh; set
# DCCRG_EXAMPLE_PLATFORM to run on another backend (the image's site
# hook pre-points JAX at a TPU tunnel, so an env default isn't enough)
_plat = os.environ.get("DCCRG_EXAMPLE_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _plat
_flags = os.environ.get("XLA_FLAGS", "")
if _plat == "cpu" and "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", _plat)


import numpy as np

from dccrg_tpu.models.advection_amr import AmrAdvection


def main(steps: int = 20, outdir: str = ".") -> None:
    amr = AmrAdvection((16, 16, 1), max_refinement_level=2)
    m0 = amr.total_mass()
    for i in range(1, steps + 1):
        amr.step()
        if i % 4 == 0:
            created, removed = amr.adapt()
            print(f"step {i}: t={amr.time:.3f} cells={len(amr.grid.get_cells())} "
                  f"(+{len(created)}/-{len(removed)})")
        if i % 10 == 0:
            amr.balance()
            amr.grid.write_vtk_file(f"{outdir}/advection_{i:05d}.vtk",
                                    fields=["density"])
    m1 = amr.total_mass()
    print(f"mass drift: {abs(m1 - m0) / m0:.2e}")
    assert abs(m1 - m0) / m0 < 1e-4
    print("PASSED")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 20,
         sys.argv[2] if len(sys.argv) > 2 else ".")
