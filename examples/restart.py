"""Checkpoint / restart walkthrough (the reference's tests/restart
story, tests/restart/README:10-14): run a refined advection problem,
save mid-flight, restart FROM NOTHING BUT THE FILE, finish both runs
and require identical results.

Run: python examples/restart.py
"""

import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from dccrg_tpu.grid import Grid  # noqa: E402
from dccrg_tpu.models.advection_amr import AmrAdvection  # noqa: E402


def main():
    app = AmrAdvection(length=(16, 16, 1), max_refinement_level=1)
    app.run(4, adapt_n=2)  # refine around the hump, advect a little

    with tempfile.TemporaryDirectory() as tmp:
        fn = str(Path(tmp) / "mid.dc")
        app.grid.save_grid_data(fn, header=b"advection-restart")

        # uninterrupted run: 4 more steps
        app.run(4)
        want = app.grid.get("density", app.grid.get_cells())

        # restart: reconstruct EVERYTHING from the file
        grid2, header = Grid.from_file(
            fn, dict(app.grid.fields), header_size=len(b"advection-restart")
        )
        print(f"restarted from {fn}: header={header!r}, "
              f"{len(grid2.plan.cells)} cells "
              f"({int(np.sum(grid2.mapping.get_refinement_level(grid2.plan.cells) > 0))} refined)")
        app2 = AmrAdvection.from_grid(grid2)
        app2.run(4)
        got = app2.grid.get("density", app2.grid.get_cells())

    err = float(np.abs(got - want).max())
    print(f"max |restarted - uninterrupted| = {err:.3e}")
    assert err < 1e-6, "restart diverged from the uninterrupted run"
    print("PASSED")


if __name__ == "__main__":
    main()
