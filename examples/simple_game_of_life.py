#!/usr/bin/env python
"""Minimal stencil application: Conway's game of life on a 10x10 grid
(reference examples/simple_game_of_life.cpp) — a blinker oscillating
for 10 turns, verified every step.

Run (defaults to a virtual 8-device CPU mesh):
    python examples/simple_game_of_life.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# examples default to the virtual 8-device CPU mesh; set
# DCCRG_EXAMPLE_PLATFORM to run on another backend (the image's site
# hook pre-points JAX at a TPU tunnel, so an env default isn't enough)
_plat = os.environ.get("DCCRG_EXAMPLE_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _plat
_flags = os.environ.get("XLA_FLAGS", "")
if _plat == "cpu" and "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", _plat)

import numpy as np

from dccrg_tpu.models.game_of_life import GameOfLife


def main() -> None:
    gol = GameOfLife(length=(10, 10, 1))

    def cid(x, y):
        return 1 + x + y * 10

    vertical = [cid(4, 3), cid(4, 4), cid(4, 5)]
    horizontal = [cid(3, 4), cid(4, 4), cid(5, 4)]
    gol.set_alive(vertical)

    for turn in range(10):
        gol.step()
        expect = horizontal if turn % 2 == 0 else vertical
        got = np.sort(gol.alive_cells())
        assert np.array_equal(got, np.sort(expect)), (turn, got)
        print(f"turn {turn + 1}: alive = {got.tolist()}")
    print("PASSED")


if __name__ == "__main__":
    main()
