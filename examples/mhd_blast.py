"""Model-zoo walkthrough: an MHD blast wave through the whole stack.

1. A magnetized Sedov-style pressure blast (GridMHD) advances via the
   two operator-split passes — the hydro Rusanov flux pass exchanges
   ONLY the hydro fields' ghosts, the CT/divergence-cleaning pass
   ONLY the B fields' — and conservation of mass/momentum/energy/B
   is checked against the integrity layer's drift tolerance.
2. The per-field ghost-split overlap (DCCRG_GHOST_SPLIT) is compared
   against the full outer re-pass: BITWISE-identical state, strictly
   fewer recomputed outer row slots (the counts are printed).
3. The same physics serves as a FLEET kernel: a mixed mini-fleet
   (advect_x + mhd + vlasov — three buckets under one scheduler)
   runs to completion with every job's digest bitwise equal to its
   solo ``Grid.run_steps`` run.

Run: python examples/mhd_blast.py
"""

import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    from dccrg_tpu import checkpoint, integrity
    from dccrg_tpu.fleet import FleetJob, run_solo
    from dccrg_tpu.models import GridMHD
    from dccrg_tpu.models.mhd import MHD_ALL
    from dccrg_tpu.scheduler import FleetScheduler

    # -- 1. the blast, conservation pinned ----------------------------
    m = GridMHD(n=12)
    before = m.conserved_sums()
    dt = m.run(20)
    after = m.conserved_sums()
    print(f"blast: 20+20 split steps at dt={dt:.4f}")
    for name in MHD_ALL:
        drift = abs(after[name] - before[name])
        tol = integrity.sum_tolerance(before[name], 12 ** 3, steps=20)
        status = "ok" if drift <= tol else "DRIFTED"
        print(f"  sum({name}): {before[name]:+.6f} -> "
              f"{after[name]:+.6f}  (|drift| {drift:.2e} "
              f"<= tol {tol:.2e}: {status})")
        assert drift <= tol, name

    # -- 2. ghost-split vs full outer re-pass -------------------------
    os.environ["DCCRG_OVERLAP"] = "1"  # CPU default is off
    digests, rows = {}, {}
    for split in ("0", "1"):
        os.environ["DCCRG_GHOST_SPLIT"] = split
        g = GridMHD(n=8, nz=40)
        g.run(5, dt=0.01)
        digests[split] = checkpoint.state_digest(g.grid)
        ov = g.grid.last_overlap
        rows[split] = (ov["mode"], ov["rows_split"], ov["rows_full"])
    os.environ.pop("DCCRG_OVERLAP")
    os.environ.pop("DCCRG_GHOST_SPLIT")
    assert digests["0"] == digests["1"], "ghost-split parity broken"
    print(f"ghost split: bitwise parity OK; cleaning-pass outer "
          f"re-pass {rows['0'][1]} -> {rows['1'][1]} row slots "
          f"(mode {rows['0'][0]} -> {rows['1'][0]})")
    assert rows["1"][1] < rows["0"][1]

    # -- 3. the mixed mini-fleet --------------------------------------
    jobs = [FleetJob(f"{k}0", kernel=k, length=(6, 6, 6), n_steps=8,
                     seed=7, checkpoint_every=4)
            for k in ("advect_x", "mhd", "vlasov")]
    solo = {j.name: run_solo(FleetJob(
        j.name, kernel=j.kernel, length=j.length, n_steps=j.n_steps,
        seed=j.seed)) for j in jobs}
    with tempfile.TemporaryDirectory(prefix="dccrg_zoo_") as wd:
        report = FleetScheduler(wd, jobs, quantum=4).run()
    for name, row in sorted(report.items()):
        match = "bitwise == solo" if row["digest"] == solo[name] \
            else "MISMATCH"
        print(f"  fleet {name}: {row['status']} at step "
              f"{row['steps']} ({match})")
        assert row["digest"] == solo[name], name
    print("mixed-kernel fleet OK: 3 kernels, 3 buckets, one "
          "scheduler, all digests solo-bitwise")


if __name__ == "__main__":
    main()
