"""Resilience walkthrough: a run that survives injected disasters.

An advection run is wrapped in ResilientRunner (atomic checksummed
checkpoints + numerics watchdog + auto-rollback) while a FaultPlan
injects a NaN blow-up mid-run and a simulated device OOM at dispatch.
The run must (a) trip, roll back and reconverge BITWISE-identically to
an undisturbed run, and (b) complete the OOM'd step through the
gather-mode fallback chain.

Run: python examples/resilient_run.py
"""

import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from dccrg_tpu import FaultPlan, ResilientRunner, resilience  # noqa: E402
from dccrg_tpu.models.advection import GridAdvection  # noqa: E402


def make_runner(tmp, name):
    solver = GridAdvection(n=16, nz=4)
    dt = 0.5 * solver.max_time_step()

    def step_fn(grid, _i):
        grid.run_steps(solver._kernel, ["density", "vx", "vy"],
                       ["density"], 1, extra_args=(jnp.float32(dt),))

    runner = ResilientRunner(
        solver.grid, step_fn, str(Path(tmp) / f"{name}.dc"),
        fields=("density",), check_every=1, checkpoint_every=5,
        backoff=0.0, diagnostics_dir=tmp)
    return solver, runner, dt


def main():
    with tempfile.TemporaryDirectory() as tmp:
        # undisturbed reference run
        ref_solver, ref_runner, _ = make_runner(tmp, "ref")
        ref_runner.run(20)
        ref = np.asarray(ref_solver.grid.get("density",
                                             ref_solver.grid.plan.cells))

        # the same run, with a NaN landing in the density field after
        # step 13 — the watchdog must trip, roll back to the step-10
        # checkpoint, and resume
        solver, runner, dt = make_runner(tmp, "guarded")
        plan = FaultPlan(seed=42)
        plan.nan_poison("density", step=13)
        with plan:
            runner.run(20)
        got = np.asarray(solver.grid.get("density",
                                         solver.grid.plan.cells))
        print(f"trips={len(runner.trips)} rollbacks={runner.rollbacks} "
              f"checkpoints={runner.checkpoints} "
              f"diag={runner.trips[0].get('path')}")
        assert runner.rollbacks == 1
        assert got.tobytes() == ref.tobytes(), \
            "recovered run diverged from the undisturbed one"
        print("rollback reconverged bitwise-identically")

        # a simulated RESOURCE_EXHAUSTED on the first dispatch: the
        # fallback chain (current -> roll -> tables) completes the step
        plan2 = FaultPlan()
        plan2.resource_exhausted(times=1, mode="current")
        with plan2:
            mode = resilience.guarded_step(
                solver.grid, solver._kernel, ["density", "vx", "vy"],
                ["density"], n_steps=1, extra_args=(jnp.float32(dt),))
        print(f"OOM'd dispatch completed in fallback gather mode {mode!r}")

    print("PASSED")


if __name__ == "__main__":
    main()
