#!/usr/bin/env python
"""Basic cell data over a refined, periodic grid (reference
examples/basic_cell_data.cpp): store each cell's own id as its data,
refresh remote copies, and verify every ghost copy carries the right
value — the smallest end-to-end proof that the halo exchange moves the
right bytes between owners.

Run (defaults to a virtual 8-device CPU mesh):
    python examples/basic_cell_data.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_plat = os.environ.get("DCCRG_EXAMPLE_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _plat
_flags = os.environ.get("XLA_FLAGS", "")
if _plat == "cpu" and "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", _plat)

import numpy as np
import jax.numpy as jnp

from dccrg_tpu.grid import Grid


def main() -> None:
    # the reference's configuration: odd lengths, refinement, a wide
    # (length-2) neighborhood, full periodicity, then a balance
    grid = (
        Grid(cell_data={"data": jnp.int32})
        .set_initial_length((7, 13, 11))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(2)
        .set_periodic(True, True, True)
        .initialize(partition="morton")
    )
    for cid in grid.local_cells().ids[::97]:  # a scattering of refines
        grid.refine_completely(int(cid))
    grid.stop_refining()
    grid.balance_load()

    # set cell id as the value for cell data
    cells = grid.plan.cells
    grid.set("data", cells, cells.astype(np.int32))

    # check that cell data is updated correctly between devices:
    # after the refresh, every ghost row must hold its cell's id
    grid.update_copies_of_remote_neighbors()
    host = np.asarray(grid.data["data"])
    L = grid.plan.L
    checked = 0
    for d in range(grid.n_dev):
        ghosts = grid.plan.ghost_ids[d]
        if len(ghosts) == 0:
            continue
        got = host[d, L : L + len(ghosts)]
        if not np.array_equal(got, ghosts.astype(np.int32)):
            bad = np.nonzero(got != ghosts.astype(np.int32))[0][:5]
            raise SystemExit(
                f"wrong ghost data on device {d}: rows {bad} hold "
                f"{got[bad]} instead of {ghosts[bad]}"
            )
        checked += len(ghosts)

    # and spot-check through the neighbor query API, as the reference
    # iterates cell.neighbors_of
    for cid in cells[:: max(1, len(cells) // 50)]:
        for nbr, _off in grid.get_neighbors_of(int(cid)):
            if nbr != 0 and grid.get("data", int(nbr)) != np.int32(nbr):
                raise SystemExit(f"wrong data for neighbor {nbr} of {cid}")

    print(f"{len(cells)} cells, {checked} ghost copies verified")
    print("PASSED")


if __name__ == "__main__":
    main()
