"""Run supervision walkthrough: a run that survives ``kill -TERM``.

An advection run is wrapped in SupervisedRunner (numbered checkpoints
with retention GC + preemption handling + step watchdog). Mid-run the
script sends ITSELF a real SIGTERM — exactly what a preemptible-fleet
scheduler does — and must (a) stop at the next step boundary with a
CRC-verified emergency checkpoint and the distinct resumable exit
code 75 (EX_TEMPFAIL), then (b) resume via ``resume_latest`` and
reconverge BITWISE-identically to an undisturbed run. A transient
dispatch error is also injected to show the retry-with-backoff path
(no rollback).

Run: python examples/preemptible_run.py
(Or start it with DCCRG_DEMO_STEPS=2000 and kill -TERM it yourself;
rerunning resumes from the emergency checkpoint.)
"""

import os
import signal
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from dccrg_tpu import (FaultPlan, PreemptedError,  # noqa: E402
                       SupervisedRunner, resilience, supervise)
from dccrg_tpu.models.advection import GridAdvection  # noqa: E402

CELL_DATA = {"density": jnp.float32, "vx": jnp.float32, "vy": jnp.float32}
N_STEPS = int(os.environ.get("DCCRG_DEMO_STEPS", "20"))


def make_runner(tmp, name, solver=None, start_step=0, extra_step=None):
    solver = solver or GridAdvection(n=16, nz=4)
    dt = 0.5 * solver.max_time_step()

    def step_fn(grid, i):
        grid.run_steps(solver._kernel, ["density", "vx", "vy"],
                       ["density"], 1, extra_args=(jnp.float32(dt),))
        if extra_step is not None:
            extra_step(grid, i)

    runner = SupervisedRunner(
        solver.grid, step_fn, str(Path(tmp) / name),
        fields=("density",), check_every=1, checkpoint_every=5,
        backoff=0.0, keep_last=3, grace=15.0, step_timeout=120.0,
        start_step=start_step)
    return solver, runner


def density(solver):
    return np.asarray(solver.grid.get("density", solver.grid.plan.cells))


def main():
    with tempfile.TemporaryDirectory() as tmp:
        # undisturbed reference run
        ref_solver, ref_runner = make_runner(tmp, "ref")
        ref_runner.run(N_STEPS)
        ref = density(ref_solver)

        # the same run, but a REAL SIGTERM lands mid-step 12 — the
        # scheduler's preemption notice. The supervisor finishes the
        # step, takes a CRC-verified emergency checkpoint inside the
        # grace window and surfaces the resumable exit code.
        def self_sigterm(_grid, i):
            if i == 12:
                os.kill(os.getpid(), signal.SIGTERM)

        solver, runner = make_runner(tmp, "pre", extra_step=self_sigterm)
        try:
            runner.run(N_STEPS)
            raise AssertionError("the SIGTERM was lost")
        except PreemptedError as e:
            print(f"preempted at step {e.step}: checkpoint {e.checkpoint} "
                  f"(exit code would be {e.exit_code})")
            assert resilience.verify_checkpoint(e.checkpoint) == []

        # a fresh process would now do exactly this: scan the store,
        # pick the newest VERIFIED checkpoint, rebuild the grid from
        # nothing but the file, continue to the end
        info = supervise.resume_latest(
            str(Path(tmp) / "pre"), CELL_DATA,
            load_balancing_method=solver.grid._lb_method)
        assert info is not None and not info.salvaged
        print(f"resuming from {info.path} (step {info.step})")
        solver2 = GridAdvection(n=16, nz=4)
        solver2.grid = info.grid
        info.grid.update_copies_of_remote_neighbors()
        solver2, runner2 = make_runner(tmp, "pre", solver=solver2,
                                       start_step=info.step)
        runner2.run(N_STEPS)
        got = density(solver2)
        assert got.tobytes() == ref.tobytes(), \
            "resumed run diverged from the undisturbed one"
        print("resumed run reconverged bitwise-identically")

        # retention GC kept only the newest checkpoints
        kept = [s for s, _ in runner2.store.list()]
        print(f"retention kept steps {kept} (keep_last=3)")
        assert len(kept) <= 3

        # and a transient dispatch error (the UNAVAILABLE class)
        # retries with backoff instead of tripping a rollback
        solver3, runner3 = make_runner(tmp, "transient")
        plan = FaultPlan(seed=7)
        plan.dispatch_error(times=2, step=4)
        with plan:
            runner3.run(10)
        print(f"transient dispatch errors retried "
              f"{runner3.dispatch_retried}x, rollbacks="
              f"{runner3.rollbacks}")
        assert runner3.dispatch_retried == 2 and runner3.rollbacks == 0

    print("PASSED")


if __name__ == "__main__":
    main()
