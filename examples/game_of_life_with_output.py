#!/usr/bin/env python
"""Game of life with checkpoint + VTK output (reference
examples/game_of_life_with_output.cpp + dc2vtk.cpp): saves the game
state to a .dc file each turn, then converts the checkpoints to VTK
with the standalone converter.

Run (defaults to a virtual 8-device CPU mesh):
    python examples/game_of_life_with_output.py [outdir]
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# examples default to the virtual 8-device CPU mesh; set
# DCCRG_EXAMPLE_PLATFORM to run on another backend (the image's site
# hook pre-points JAX at a TPU tunnel, so an env default isn't enough)
_plat = os.environ.get("DCCRG_EXAMPLE_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _plat
_flags = os.environ.get("XLA_FLAGS", "")
if _plat == "cpu" and "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", _plat)


import numpy as np

from dccrg_tpu.models.game_of_life import GameOfLife
from dccrg_tpu.utils import dc_to_vtk


def main(outdir: str = ".") -> None:
    gol = GameOfLife(length=(10, 10, 1))
    gol.set_alive([1 + 4 + y * 10 for y in (3, 4, 5)])

    fields = {"live": ((), np.int32), "total": ((), np.int32)}
    for turn in range(5):
        dc = f"{outdir}/gol_{turn:05d}.dc"
        gol.grid.save_grid_data(dc)
        dc_to_vtk(dc, dc.replace(".dc", ".vtk"), fields=fields)
        print(f"turn {turn}: wrote {dc} (+ .vtk), "
              f"{len(gol.alive_cells())} cells alive")
        gol.step()
    print("PASSED")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else ".")
