#!/usr/bin/env python
"""Benchmark driver: advection 3-D cell-updates/sec on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``value`` is the FRAMEWORK (grid-path) throughput and is null when
that leg fails — the specialized Pallas kernel bound is published
separately under ``pallas_metric`` / ``pallas_updates_per_sec`` and is
never substituted into the headline.

Workload: the reference's north-star configuration (BASELINE.json) —
tests/advection 3-D 512^3 uniform grid (max_refinement_level 0),
first-order upwind solid-body rotation — on the real TPU chip via the
dense fast path (dccrg_tpu/models/advection.py).

Baseline: the reference repo publishes no advection numbers and cannot
be built here (no MPI/Zoltan/boost toolchain), so the baseline is
measured on this host: the identical math as a -O3 C++ loop
(bench/baseline_advection.cpp), single core, scaled by a nominal
32-core HPC node with perfect MPI scaling — a deliberately generous
stand-in for "single-node MPI cell-updates/sec". Cached in
bench/baseline_measured.json.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent
NODE_CORES = 32  # nominal single-node core count for the MPI baseline
N = int(os.environ.get("BENCH_N", "512"))
NZ = int(os.environ.get("BENCH_NZ", str(N)))
STEPS = int(os.environ.get("BENCH_STEPS", "20"))


def measure_baseline() -> float:
    """Single-node reference throughput: the C++ upwind loop
    (bench/baseline_advection.cpp, the reference's solve.hpp math) at
    the bench's own per-core problem size, fork-parallel across the
    host's cores. When the host has fewer cores than the nominal
    32-core node, the concurrent measurement is extrapolated to
    NODE_CORES at perfect MPI scaling — deliberately generous to the
    reference (tests/advection/2d.cpp:453-503 reports per-rank sums) —
    so a 1-core build host still yields a full-node bar. The cache
    records both the measured aggregate and the node figure; the bench
    compares against the node figure."""
    cache = ROOT / "bench" / "baseline_measured.json"
    if cache.exists():
        got = json.loads(cache.read_text())
        if "node_cell_updates_per_sec" in got:  # current-format cache only
            return got["node_cell_updates_per_sec"]
    exe = ROOT / "bench" / "baseline_advection"
    src = ROOT / "bench" / "baseline_advection.cpp"
    subprocess.run(
        ["g++", "-O3", "-march=native", "-o", str(exe), str(src)],
        check=True, capture_output=True,
    )
    cores = max(1, min(os.cpu_count() or 1, NODE_CORES))
    # the bench size split across cores (as an MPI run would be), at
    # least a few z-planes per rank
    nzp = max(8, NZ // cores)
    steps = 3

    def trial():
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen([str(exe), str(N), str(nzp), str(steps)],
                             stdout=subprocess.PIPE, text=True)
            for _ in range(cores)
        ]
        for p in procs:
            p.wait()
        wall = time.perf_counter() - t0
        for p in procs:
            if p.returncode != 0:
                raise RuntimeError("baseline_advection failed")
        return [float(p.stdout.read().strip()) for p in procs], wall

    # best of 3: the baseline must not be deflated by transient load on
    # a shared host (that would flatter vs_baseline)
    trials = [trial() for _ in range(3)]
    per_core_internal, wall = max(trials, key=lambda t: sum(t[0]))
    # each process times its own stepping loop while all run
    # concurrently: the sum is the host throughput under real memory
    # contention, without charging process startup to the reference
    measured_rate = sum(per_core_internal)
    # extrapolate to the nominal node width at perfect scaling when the
    # host is narrower than a node (generous to the reference: real MPI
    # scaling is sublinear under shared-memory-bandwidth contention)
    node_rate = measured_rate * (NODE_CORES / cores)
    result = {
        "single_core_cell_updates_per_sec": max(per_core_internal),
        "measured_aggregate_cell_updates_per_sec": measured_rate,
        "node_cell_updates_per_sec": node_rate,
        "node_cores_used": cores,
        "node_cores_nominal": NODE_CORES,
        "node_extrapolated": cores < NODE_CORES,
        "per_core_size": [N, nzp, steps],
        "wall_seconds": wall,
    }
    cache.write_text(json.dumps(result, indent=1))
    return node_rate


GRID_N = int(os.environ.get("BENCH_GRID_N", "512"))  # north-star size
GRID_STEPS = int(os.environ.get("BENCH_GRID_STEPS", "20"))
AB_N = int(os.environ.get("BENCH_AB_N", "128"))
AB_STEPS = int(os.environ.get("BENCH_AB_STEPS", "10"))


def bench_pallas(baseline):
    """The Pallas temporal-blocked fast path at the north-star size.
    BENCH_PALLAS_DTYPE=bfloat16 runs the narrow-storage variant (the
    kernel's flux arithmetic is weakly typed, so state stays bf16 in
    VMEM and HBM — roughly half the traffic of f32 on chip)."""
    import jax
    import jax.numpy as jnp
    from dccrg_tpu.models.advection import PallasRotationAdvection, analytic_density
    import numpy as np

    pdt = jnp.dtype(os.environ.get("BENCH_PALLAS_DTYPE", "float32"))
    solver = PallasRotationAdvection(n=N, nz=NZ, dtype=pdt)
    dt = 0.5 * solver.max_time_step()

    # warmup / compile, synced by a forced scalar readback (a device
    # reduction pulled to host cannot under-report through the tunnel
    # the way block_until_ready can)
    solver.step(dt)
    float(jnp.sum(solver.rho))

    t0 = time.perf_counter()
    for _ in range(STEPS):
        solver.step(dt)
    checksum = float(jnp.sum(solver.rho))
    elapsed = time.perf_counter() - t0
    assert np.isfinite(checksum)

    n_cells = N * N * NZ
    updates_per_sec = n_cells * STEPS * solver.steps_per_pass / elapsed
    pallas_dtype = str(pdt)
    x = (np.arange(N) + 0.5) / N
    exact = np.asarray(
        analytic_density(x[:, None, None], x[None, :, None], solver.time)
    ) * np.ones((1, 1, NZ))
    diff = np.asarray(solver.rho, dtype=np.float64) - exact
    l2 = float(np.sqrt(np.sum(diff**2) * (1.0 / N) ** 2 * (1.0 / NZ)))
    print(
        f"pallas: elapsed {elapsed:.3f}s for {STEPS} passes x "
        f"{solver.steps_per_pass} steps; l2 {l2:.2e}",
        file=sys.stderr,
    )
    return updates_per_sec, l2, pallas_dtype


def bench_grid_path(n=None, steps=None, label="grid path", dtype=None):
    """The general Grid runtime (closed-form plan / gather tables +
    fused run_steps) on the same physics — the framework path an AMR
    user exercises, at max_refinement_level 0
    (tests/advection/2d.cpp:327-343). Cell-updates/sec accounting
    mirrors the reference's own benchmark (2d.cpp:316-350)."""
    from dccrg_tpu.models.advection import GridAdvection
    import numpy as np

    n = n if n is not None else GRID_N
    steps = steps if steps is not None else GRID_STEPS
    if dtype is None and os.environ.get("BENCH_GRID_DTYPE"):
        # BENCH_GRID_DTYPE=bfloat16: grid-wide narrow storage for the
        # main leg (chip_session's bulk-executor bf16 point)
        import jax.numpy as jnp

        dtype = jnp.dtype(os.environ["BENCH_GRID_DTYPE"])
    kw = {} if dtype is None else {"dtype": dtype}
    solver = GridAdvection(n=n, nz=n, **kw)
    dt = 0.5 * solver.max_time_step()

    solver.run(1, dt)  # warmup / compile
    solver.checksum()  # forced scalar readback

    t0 = time.perf_counter()
    solver.run(steps, dt)
    checksum = solver.checksum()
    elapsed = time.perf_counter() - t0
    assert np.isfinite(checksum)
    # record only the engagement BIT for the pallas-bulk leg —
    # keeping the whole Grid alive here would pin gigabytes of HBM
    # (fields + plan tables at 512^3) across the remaining legs
    global _BULK_ENGAGED
    _BULK_ENGAGED = any(k[0] == "bulksteploop"
                        for k in solver.grid._program_cache)

    n_cells = n * n * n
    updates_per_sec = n_cells * steps / elapsed
    l2 = solver.l2_error()
    print(
        f"{label}: elapsed {elapsed:.3f}s for {steps} fused steps at "
        f"{n}^3; l2 {l2:.2e}",
        file=sys.stderr,
    )
    return updates_per_sec, l2


_GATHER_VARS = ("DCCRG_FORCE_TABLES", "DCCRG_ROLL_STENCIL")


_BULK_ENGAGED = False  # did the most recent grid leg compile the bulk program


def bench_grid_path_pallas(xla_ups, xla_l2):
    """The roll-plan Pallas bulk executor (DCCRG_BULK=pallas,
    ops/roll_executor.py) on the SAME grid-path workload: the
    framework step loop compiled as tiled, double-buffered Pallas bulk
    passes with fused fixup epilogues. Reported under its own JSON key
    (null on failure — the pallas_metric discipline); the leg is
    VOIDED unless the executor provably engaged (the bulk program in
    the grid's cache — forced table mode from the A/B would otherwise
    silently rebrand the XLA table path) and L2 parity against the
    XLA roll path holds. Skipped when the user exported DCCRG_BULK
    themselves (the headline leg already ran their mode)."""
    if os.environ.get("BENCH_SKIP_BULK") == "1" or xla_ups is None:
        return None, None, None
    if os.environ.get("DCCRG_BULK", "").lower() == "pallas":
        return None, None, "user-ran-headline-as-pallas"
    saved = {v: os.environ.get(v) for v in _GATHER_VARS}
    # the executor needs the closed-form plan: forced dense tables
    # (a tables-winning A/B) would disable it at plan build
    _set_gather_mode("roll")
    os.environ["DCCRG_BULK"] = "pallas"
    try:
        ups, l2 = bench_grid_path(label="grid path pallas-bulk")
    except Exception as e:
        print(f"pallas-bulk grid leg failed ({e!r})", file=sys.stderr)
        return None, None, f"failed: {e!r}"
    finally:
        os.environ.pop("DCCRG_BULK", None)
        for v, val in saved.items():
            if val is None:
                os.environ.pop(v, None)
            else:
                os.environ[v] = val
    if not _BULK_ENGAGED:
        print("pallas-bulk leg: executor did NOT engage (ineligible "
              "plan?); leg voided", file=sys.stderr)
        return None, l2, "executor-did-not-engage"
    if xla_l2 is not None and abs(l2 - xla_l2) > 1e-3 + 0.05 * abs(xla_l2):
        print(f"pallas-bulk L2 {l2:.3e} vs xla {xla_l2:.3e}: parity "
              "FAILED; leg voided", file=sys.stderr)
        return None, l2, "l2-parity-failed"
    return ups, l2, None


def _set_gather_mode(mode):
    """Force one gather mode: 'roll' (closed-form plan, rolls forced
    even where the platform default is tables — e.g. the CPU backend)
    or 'tables' (dense gather tables, random gathers)."""
    if mode == "tables":
        os.environ["DCCRG_FORCE_TABLES"] = "1"
        os.environ["DCCRG_ROLL_STENCIL"] = "0"
    else:
        os.environ.pop("DCCRG_FORCE_TABLES", None)
        os.environ["DCCRG_ROLL_STENCIL"] = "1"


def ab_roll_vs_tables():
    """On-chip A/B at a quick size: closed-form roll-decomposed
    gathers vs dense gather tables + random gathers. Returns the
    winning mode name plus both rates — the round-3 verdict's open
    question (the roll default was chosen on theory; this measures it
    wherever the bench runs). User-exported gather overrides are
    respected: the A/B is skipped so the main leg runs the caller's
    explicit settings."""
    if os.environ.get("BENCH_SKIP_AB") == "1" or any(
            v in os.environ for v in _GATHER_VARS):
        return None, None, None, None
    try:
        _set_gather_mode("roll")
        roll_ups, _ = bench_grid_path(AB_N, AB_STEPS, label="A/B roll")
        _set_gather_mode("tables")
        table_ups, _ = bench_grid_path(AB_N, AB_STEPS, label="A/B tables")
    except Exception as e:
        print(f"A/B leg failed ({e!r}); keeping roll default",
              file=sys.stderr)
        _set_gather_mode("roll")
        return None, None, None, None
    winner = "roll" if roll_ups >= table_ups else "tables"
    if winner == "tables":
        # dense tables at the main size cost ~5 bytes x cells x slots
        # plus same-size build temporaries; a host OOM kill would skip
        # the JSON line entirely, so cap the mode at a memory budget
        # (default 16 GiB — a TPU-VM host comfortably holds the 512^3
        # build; the override is recorded in the JSON when it fires)
        est = GRID_N ** 3 * 6 * 5 * 2
        cap = int(os.environ.get("BENCH_TABLES_MEM_CAP", str(16 << 30)))
        if est > cap:
            print(
                f"A/B picked tables but {GRID_N}^3 table build (~{est>>30}"
                f" GiB) exceeds BENCH_TABLES_MEM_CAP; keeping roll",
                file=sys.stderr,
            )
            return "roll", roll_ups, table_ups, "tables-won-but-mem-capped"
    print(
        f"A/B at {AB_N}^3: roll {roll_ups:.3g}/s vs tables "
        f"{table_ups:.3g}/s -> {winner}",
        file=sys.stderr,
    )
    return winner, roll_ups, table_ups, None


def ab_overlap():
    """Quick-size A/B of the overlapped fused step (DCCRG_OVERLAP)
    against the sequential exchange->kernel order. On a single chip the
    mesh has one device, so this only measures when >1 device is
    visible; the record tells whether the accelerator-default overlap
    earns its outer re-pass on real hardware. Skipped when the user
    exported DCCRG_OVERLAP explicitly."""
    import jax

    if (os.environ.get("BENCH_SKIP_AB") == "1"
            or "DCCRG_OVERLAP" in os.environ or len(jax.devices()) < 2):
        return None, None
    try:
        os.environ["DCCRG_OVERLAP"] = "0"
        seq, _ = bench_grid_path(AB_N, AB_STEPS, label="A/B sequential")
        os.environ["DCCRG_OVERLAP"] = "1"
        ovl, _ = bench_grid_path(AB_N, AB_STEPS, label="A/B overlap")
    except Exception as e:
        print(f"overlap A/B failed ({e!r})", file=sys.stderr)
        return None, None
    finally:
        os.environ.pop("DCCRG_OVERLAP", None)
    print(f"A/B overlap at {AB_N}^3: sequential {seq:.3g}/s vs "
          f"overlap {ovl:.3g}/s", file=sys.stderr)
    return seq, ovl


def probe_backend(timeout_s: int = 150) -> bool:
    """Check that the accelerator backend actually answers before any
    in-process jax.devices() call: a hung device tunnel would otherwise
    hang the whole bench without emitting the JSON line the driver
    records. Routed through resilience.safe_devices — a subprocess
    probe with hard-kill timeout escalation and bounded retries (the
    axon client is known to survive SIGTERM). ``BENCH_PLATFORM=cpu``
    targets the CPU backend instead (validation runs when no chip is
    reachable; the image's site hook pre-sets JAX_PLATFORMS=axon, so
    the override must go through jax.config)."""
    from dccrg_tpu.resilience import DeviceProbeError, safe_devices

    plat = os.environ.get("BENCH_PLATFORM", "") or None
    try:
        safe_devices(timeout=timeout_s, retries=1, backoff=2.0,
                     platform=plat)
        return True
    except DeviceProbeError as e:
        print(f"device probe failed: {e}", file=sys.stderr)
        return False


def main() -> None:
    baseline = measure_baseline()

    if not probe_backend():
        print(
            "device backend unreachable (probe timed out); no benchmark "
            "was run", file=sys.stderr,
        )
        print(json.dumps({
            "metric": (f"grid-path advection 3D {GRID_N}^3 "
                       "cell-updates/sec/chip"),
            "value": None,
            "unit": "cell-updates/s",
            "vs_baseline": None,
            "error": "TPU backend unreachable (device probe timed out)",
        }))
        return

    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    user_env = {v: os.environ[v] for v in _GATHER_VARS if v in os.environ}
    ab_seq, ab_ovl = ab_overlap()
    winner, ab_roll, ab_tables, ab_note = ab_roll_vs_tables()
    if winner is not None:
        mode_used, mode_source = winner, ("ab" if ab_note is None
                                          else "ab-mem-capped")
        _set_gather_mode(winner)
    else:
        # user-exported overrides (A/B skipped): tables when dense
        # tables or table gathers were explicitly requested
        mode_used = ("tables"
                     if (user_env.get("DCCRG_FORCE_TABLES") == "1"
                         or user_env.get("DCCRG_ROLL_STENCIL") == "0")
                     else "roll")
        mode_source = "user-env" if user_env else "default"
    try:
        grid_ups, grid_l2 = bench_grid_path()
    except Exception as e:
        other = "roll" if mode_used == "tables" else "tables"
        print(f"grid path bench failed ({e!r}); retrying with "
              f"{other} gathers", file=sys.stderr)
        _set_gather_mode(other)
        mode_used, mode_source = other, "fallback-after-failure"
        try:
            grid_ups, grid_l2 = bench_grid_path()
        except Exception as e2:  # keep the JSON line flowing for the driver
            print(f"grid path bench failed again: {e2!r}", file=sys.stderr)
            grid_ups, grid_l2 = None, None
    # snapshot the HEADLINE leg's bulk engagement before later legs
    # overwrite the flag: a DCCRG_BULK=pallas run whose executor
    # silently fell back (ineligible plan, multi-device mesh) must not
    # report its XLA numbers as the Pallas executor's
    headline_bulk_engaged = _BULK_ENGAGED
    # bfloat16 storage leg (float32 compute): halves the stencil's HBM
    # traffic — reported separately, the headline stays float32 (the
    # reference computes in double; f32 is already the recorded
    # departure, bf16 is the optional narrow-storage mode)
    bf16_ups = bf16_l2 = None
    if os.environ.get("BENCH_SKIP_BF16") != "1" and grid_ups is not None:
        try:
            import jax.numpy as jnp
            bf16_ups, bf16_l2 = bench_grid_path(
                label="grid path bf16", dtype=jnp.bfloat16)
        except Exception as e:
            print(f"bf16 leg failed ({e!r})", file=sys.stderr)
    # the bulk-executor leg rides the same gather mode as the headline
    # (the executor replaces the whole step program, but its XLA
    # fallback paths should match the measured configuration)
    bulk_ups, bulk_l2, bulk_note = bench_grid_path_pallas(grid_ups, grid_l2)
    # restore the caller's gather settings for the Pallas leg
    for v in _GATHER_VARS:
        os.environ.pop(v, None)
    os.environ.update(user_env)
    try:
        pallas_ups, pallas_l2, pallas_dt = bench_pallas(baseline)
    except Exception as e:  # the specialized kernel is secondary
        print(f"pallas bench failed ({e!r})", file=sys.stderr)
        pallas_ups, pallas_l2, pallas_dt = None, None, "not-run"

    # headline value = the FRAMEWORK (general Grid runtime) throughput
    # at the north-star size; the Pallas figure is the specialized
    # single-kernel bound, published under its OWN metric name — when
    # the grid leg fails the headline is null, never the Pallas bound
    # (round-5 advisor item: a 7.6e10 'grid-path' value measured on the
    # specialized kernel misleads downstream consumers)
    print(
        json.dumps(
            {
                "metric": (f"grid-path advection 3D {GRID_N}^3 "
                           "cell-updates/sec/chip"),
                "value": grid_ups,
                "unit": "cell-updates/s",
                "vs_baseline": (grid_ups / baseline
                                if grid_ups is not None else None),
                "grid_path_updates_per_sec": grid_ups,
                "grid_path_size": f"{GRID_N}^3",
                "grid_path_vs_baseline": (grid_ups / baseline
                                          if grid_ups is not None else None),
                "l2_error": grid_l2,
                "gather_mode": mode_used,
                "gather_mode_source": mode_source,
                "ab_roll_updates_per_sec": ab_roll,
                "ab_tables_updates_per_sec": ab_tables,
                "ab_sequential_updates_per_sec": ab_seq,
                "ab_overlap_updates_per_sec": ab_ovl,
                "bf16_updates_per_sec": bf16_ups,
                "bf16_l2_error": bf16_l2,
                "grid_path_pallas_updates_per_sec": bulk_ups,
                "grid_path_pallas_l2_error": bulk_l2,
                "grid_path_pallas_vs_xla": (bulk_ups / grid_ups
                                            if bulk_ups is not None
                                            and grid_ups else None),
                "grid_path_pallas_note": bulk_note,
                # the headline leg's ACTUAL mode: "pallas" only when
                # the bulk program provably compiled; a requested-but-
                # fallen-back run is labeled so the chip session's
                # bulk A/B can never rebrand XLA numbers
                "dccrg_bulk_mode": (
                    ("pallas" if headline_bulk_engaged
                     else "pallas-requested-not-engaged")
                    if os.environ.get("DCCRG_BULK", "").lower() == "pallas"
                    else "xla"),
                "pallas_metric": (f"pallas-kernel advection 3D {N}^2x{NZ} "
                                  "cell-updates/sec/chip"),
                "pallas_updates_per_sec": pallas_ups,
                "pallas_vs_baseline": (pallas_ups / baseline
                                       if pallas_ups is not None else None),
                "pallas_l2_error": pallas_l2,
                "pallas_note": ("specialized temporal-blocked kernel bound, "
                                f"{N}^2x{NZ} {pallas_dt}"
                                "; not the framework path"),
                "baseline_node_updates_per_sec": baseline,
                "baseline_note": (f"measured C++ upwind loop, extrapolated "
                                  f"to a {NODE_CORES}-core node at perfect "
                                  "MPI scaling (bench/baseline_measured"
                                  ".json has the raw measurement)"),
                "error": (None if grid_ups is not None else
                          ("grid path failed; the specialized-kernel "
                           "bound is under pallas_metric"
                           if pallas_ups is not None
                           else "grid path AND pallas legs failed")),
            }
        )
    )
    # diagnostics on stderr only
    print(
        f"baseline {baseline:.3g}/s ({NODE_CORES}-core node equivalent); "
        f"DCCRG_BULK={os.environ.get('DCCRG_BULK') or 'xla (default)'}; "
        f"devices {jax.devices()}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
