#!/usr/bin/env python
"""Benchmark driver: advection 3-D cell-updates/sec on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: the reference's north-star configuration (BASELINE.json) —
tests/advection 3-D 512^3 uniform grid (max_refinement_level 0),
first-order upwind solid-body rotation — on the real TPU chip via the
dense fast path (dccrg_tpu/models/advection.py).

Baseline: the reference repo publishes no advection numbers and cannot
be built here (no MPI/Zoltan/boost toolchain), so the baseline is
measured on this host: the identical math as a -O3 C++ loop
(bench/baseline_advection.cpp), single core, scaled by a nominal
32-core HPC node with perfect MPI scaling — a deliberately generous
stand-in for "single-node MPI cell-updates/sec". Cached in
bench/baseline_measured.json.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent
NODE_CORES = 32  # nominal single-node core count for the MPI baseline
N = int(os.environ.get("BENCH_N", "512"))
NZ = int(os.environ.get("BENCH_NZ", str(N)))
STEPS = int(os.environ.get("BENCH_STEPS", "20"))


def measure_baseline() -> float:
    """Single-node reference throughput, MEASURED: the C++ upwind loop
    (bench/baseline_advection.cpp, the reference's solve.hpp math) at
    the bench's own per-core problem size, fork-parallel across the
    host's cores (capped at a nominal node width). No perfect-scaling
    assumption: the figure is total updates / wall time of the
    concurrently running processes, and the cache records the core
    count actually used."""
    cache = ROOT / "bench" / "baseline_measured.json"
    if cache.exists():
        got = json.loads(cache.read_text())
        if "node_cores_used" in got:  # new-format cache only
            return got["single_node_cell_updates_per_sec"]
    exe = ROOT / "bench" / "baseline_advection"
    src = ROOT / "bench" / "baseline_advection.cpp"
    subprocess.run(
        ["g++", "-O3", "-march=native", "-o", str(exe), str(src)],
        check=True, capture_output=True,
    )
    cores = max(1, min(os.cpu_count() or 1, NODE_CORES))
    # the bench size split across cores (as an MPI run would be), at
    # least a few z-planes per rank
    nzp = max(8, NZ // cores)
    steps = 3
    t0 = time.perf_counter()
    procs = [
        subprocess.Popen([str(exe), str(N), str(nzp), str(steps)],
                         stdout=subprocess.PIPE, text=True)
        for _ in range(cores)
    ]
    for p in procs:
        p.wait()
    wall = time.perf_counter() - t0
    for p in procs:
        if p.returncode != 0:
            raise RuntimeError("baseline_advection failed")
    per_core_internal = [float(p.stdout.read().strip()) for p in procs]
    # each process times its own stepping loop while all run
    # concurrently: the sum is the node throughput under real memory
    # contention, without charging process startup to the reference
    node_rate = sum(per_core_internal)
    result = {
        "single_core_cell_updates_per_sec": max(per_core_internal),
        "single_node_cell_updates_per_sec": node_rate,
        "node_cores_used": cores,
        "per_core_size": [N, nzp, steps],
        "wall_seconds": wall,
    }
    cache.write_text(json.dumps(result, indent=1))
    return node_rate


GRID_N = int(os.environ.get("BENCH_GRID_N", "256"))
GRID_STEPS = int(os.environ.get("BENCH_GRID_STEPS", "20"))


def bench_pallas(baseline):
    """The Pallas temporal-blocked fast path at the north-star size."""
    import jax
    import jax.numpy as jnp
    from dccrg_tpu.models.advection import PallasRotationAdvection, analytic_density
    import numpy as np

    solver = PallasRotationAdvection(n=N, nz=NZ)
    dt = 0.5 * solver.max_time_step()

    # warmup / compile, synced by a forced scalar readback (a device
    # reduction pulled to host cannot under-report through the tunnel
    # the way block_until_ready can)
    solver.step(dt)
    float(jnp.sum(solver.rho))

    t0 = time.perf_counter()
    for _ in range(STEPS):
        solver.step(dt)
    checksum = float(jnp.sum(solver.rho))
    elapsed = time.perf_counter() - t0
    assert np.isfinite(checksum)

    n_cells = N * N * NZ
    updates_per_sec = n_cells * STEPS * solver.steps_per_pass / elapsed
    x = (np.arange(N) + 0.5) / N
    exact = np.asarray(
        analytic_density(x[:, None, None], x[None, :, None], solver.time)
    ) * np.ones((1, 1, NZ))
    diff = np.asarray(solver.rho, dtype=np.float64) - exact
    l2 = float(np.sqrt(np.sum(diff**2) * (1.0 / N) ** 2 * (1.0 / NZ)))
    print(
        f"pallas: elapsed {elapsed:.3f}s for {STEPS} passes x "
        f"{solver.steps_per_pass} steps; l2 {l2:.2e}",
        file=sys.stderr,
    )
    return updates_per_sec, l2


def bench_grid_path(baseline):
    """The general Grid runtime (gather tables + fused run_steps) on
    the same physics — the framework path an AMR user exercises, at
    max_refinement_level 0 (tests/advection/2d.cpp:327-343)."""
    from dccrg_tpu.models.advection import GridAdvection
    import numpy as np

    solver = GridAdvection(n=GRID_N, nz=GRID_N)
    dt = 0.5 * solver.max_time_step()

    solver.run(1, dt)  # warmup / compile
    solver.checksum()  # forced scalar readback

    t0 = time.perf_counter()
    solver.run(GRID_STEPS, dt)
    checksum = solver.checksum()
    elapsed = time.perf_counter() - t0
    assert np.isfinite(checksum)

    n_cells = GRID_N * GRID_N * GRID_N
    updates_per_sec = n_cells * GRID_STEPS / elapsed
    l2 = solver.l2_error()
    print(
        f"grid path: elapsed {elapsed:.3f}s for {GRID_STEPS} fused steps at "
        f"{GRID_N}^3; l2 {l2:.2e}",
        file=sys.stderr,
    )
    return updates_per_sec, l2


def probe_backend(timeout_s: int = 150) -> bool:
    """Check in a SUBPROCESS that the accelerator backend actually
    answers: a hung device tunnel would otherwise hang the whole bench
    without emitting the JSON line the driver records."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=timeout_s, capture_output=True, text=True,
        )
        return out.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    baseline = measure_baseline()

    if not probe_backend():
        print(
            "device backend unreachable (probe timed out); no benchmark "
            "was run", file=sys.stderr,
        )
        print(json.dumps({
            "metric": f"advection 3D {N}^2x{NZ} cell-updates/sec/chip",
            "value": 0,
            "unit": "cell-updates/s",
            "vs_baseline": 0,
            "error": "TPU backend unreachable (device probe timed out)",
        }))
        return

    import jax

    pallas_ups, pallas_l2 = bench_pallas(baseline)
    try:
        grid_ups, grid_l2 = bench_grid_path(baseline)
    except Exception as e:
        print(f"grid path bench failed ({e!r}); retrying with table "
              "gathers (DCCRG_ROLL_STENCIL=0)", file=sys.stderr)
        os.environ["DCCRG_ROLL_STENCIL"] = "0"
        try:
            grid_ups, grid_l2 = bench_grid_path(baseline)
        except Exception as e2:  # keep the JSON line flowing for the driver
            print(f"grid path bench failed again: {e2!r}", file=sys.stderr)
            grid_ups, grid_l2 = None, None

    print(
        json.dumps(
            {
                "metric": f"advection 3D {N}^2x{NZ} cell-updates/sec/chip",
                "value": pallas_ups,
                "unit": "cell-updates/s",
                "vs_baseline": pallas_ups / baseline,
                "pallas_updates_per_sec": pallas_ups,
                "pallas_l2_error": pallas_l2,
                "grid_path_updates_per_sec": grid_ups,
                "grid_path_size": f"{GRID_N}^3",
                "grid_path_vs_baseline": (grid_ups / baseline
                                          if grid_ups is not None else None),
                "l2_error": grid_l2,
            }
        )
    )
    # diagnostics on stderr only
    print(
        f"baseline {baseline:.3g}/s (single-core x {NODE_CORES}); "
        f"devices {jax.devices()}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
