#!/usr/bin/env python
"""Benchmark driver: advection 3-D cell-updates/sec on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: the reference's north-star configuration (BASELINE.json) —
tests/advection 3-D 512^3 uniform grid (max_refinement_level 0),
first-order upwind solid-body rotation — on the real TPU chip via the
dense fast path (dccrg_tpu/models/advection.py).

Baseline: the reference repo publishes no advection numbers and cannot
be built here (no MPI/Zoltan/boost toolchain), so the baseline is
measured on this host: the identical math as a -O3 C++ loop
(bench/baseline_advection.cpp), single core, scaled by a nominal
32-core HPC node with perfect MPI scaling — a deliberately generous
stand-in for "single-node MPI cell-updates/sec". Cached in
bench/baseline_measured.json.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent
NODE_CORES = 32  # nominal single-node core count for the MPI baseline
N = int(os.environ.get("BENCH_N", "512"))
NZ = int(os.environ.get("BENCH_NZ", str(N)))
STEPS = int(os.environ.get("BENCH_STEPS", "20"))


def measure_baseline() -> float:
    cache = ROOT / "bench" / "baseline_measured.json"
    if cache.exists():
        return json.loads(cache.read_text())["single_node_cell_updates_per_sec"]
    exe = ROOT / "bench" / "baseline_advection"
    src = ROOT / "bench" / "baseline_advection.cpp"
    subprocess.run(
        ["g++", "-O3", "-march=native", "-o", str(exe), str(src)],
        check=True, capture_output=True,
    )
    # modest size to keep runtime sane on one core
    out = subprocess.run(
        [str(exe), "256", "64", "3"], check=True, capture_output=True, text=True
    )
    per_core = float(out.stdout.strip())
    result = {
        "single_core_cell_updates_per_sec": per_core,
        "single_node_cell_updates_per_sec": per_core * NODE_CORES,
        "node_cores_assumed": NODE_CORES,
    }
    cache.write_text(json.dumps(result, indent=1))
    return result["single_node_cell_updates_per_sec"]


def main() -> None:
    baseline = measure_baseline()

    import jax
    from dccrg_tpu.models.advection import PallasRotationAdvection

    solver = PallasRotationAdvection(n=N, nz=NZ)
    dt = 0.5 * solver.max_time_step()

    # warmup / compile
    solver.step(dt)
    jax.block_until_ready(solver.rho)

    t0 = time.perf_counter()
    for _ in range(STEPS):
        solver.step(dt)
    jax.block_until_ready(solver.rho)
    elapsed = time.perf_counter() - t0

    n_cells = N * N * NZ
    updates_per_sec = n_cells * STEPS * solver.steps_per_pass / elapsed
    print(
        json.dumps(
            {
                "metric": f"advection 3D {N}^2x{NZ} cell-updates/sec/chip",
                "value": updates_per_sec,
                "unit": "cell-updates/s",
                "vs_baseline": updates_per_sec / baseline,
            }
        )
    )
    # diagnostics on stderr only
    print(
        f"elapsed {elapsed:.3f}s for {STEPS} steps; baseline {baseline:.3g}/s "
        f"(single-core x {NODE_CORES}); devices {jax.devices()}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
